"""Flagship transformer family — GPT-style decoder / BERT-style encoder, TPU-first.

The reference wraps *user* torch models and ships only fused kernels for them
(DeepSpeedTransformerLayer, csrc/transformer/*; model zoo in tests:
tests/unit/simple_model.py, tests/unit/modeling.py BERT). Here the model family
is in-tree and TPU-native:

  - flax.linen modules, bf16 compute / fp32 params (engine holds fp32 master)
  - layers run under `nn.scan` (one compiled block body for all layers — the
    XLA-friendly equivalent of the reference's per-layer CUDA kernel reuse) with
    optional `nn.remat` (activation checkpointing, reference:
    runtime/activation_checkpointing/checkpointing.py)
  - Megatron-style tensor parallelism expressed as sharding *rules*
    (`TransformerConfig.tp_rules()`): qkv/fc1 column-parallel, proj/fc2
    row-parallel, vocab-parallel embedding. XLA inserts the psum/allgather the
    reference delegates to an external mpu object.
  - attention dispatches through ops.attention (Pallas flash on TPU)

Batch contract: a dict with "input_ids" [B, S] (+ optional "labels",
"attention_mask", "position_ids"); the module returns logits and
`causal_lm_loss` / `masked_lm_loss` turn them into the scalar loss the engine
expects (reference contract: loss = engine(batch)).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dropout: float = 0.0
    causal: bool = True            # False => BERT-style bidirectional encoder
    tie_embeddings: bool = True
    use_bias: bool = True
    dtype: Dtype = jnp.bfloat16    # compute dtype; params are fp32 (master in engine)
    remat: bool = False            # activation checkpointing of each block
    # remat policy: "full" recomputes everything (min memory, +~33% flops);
    # "dots" saves matmul outputs and recomputes elementwise only (the
    # selective-checkpointing middle ground the reference approximates with
    # per-layer checkpoint granularity, runtime/activation_checkpointing/
    # checkpointing.py:372)
    remat_policy: str = "dots"
    scan_layers: bool = True       # lax.scan over layers (fast compile, ZeRO-3-friendly)
    # fused_loss: __call__ returns the scalar causal-LM loss directly, computing
    # the vocab projection chunk-wise over the sequence so the fp32 [B,S,V]
    # logits are never materialized (HBM: ~3GB saved at 350M/bs8/seq1024)
    fused_loss: bool = False
    loss_chunk: int = 128
    # "auto" | "flash" | "reference" | "ring" | "ulysses" | "sparse"
    # (ring/ulysses: sequence parallelism, wired by the engine from the
    # sequence_parallel config section; sparse: block-sparse layouts from
    # the sparse_attention section — see the sparse_attention field)
    attention_impl: str = "auto"
    layer_norm_eps: float = 1e-5
    # -- architecture knobs covering the HF import policies (models/hf.py;
    #    reference: module_inject/replace_policy.py's per-arch policies) -----
    activation: str = "gelu"       # gelu (tanh) | gelu_exact | relu
    attn_scale: Optional[float] = None   # None = 1/sqrt(head_dim); GPT-Neo: 1.0
    pos_embed: str = "learned"     # learned | rotary (GPT-J) | alibi (BLOOM) | none
    rotary_dim: int = 0            # 0 = whole head_dim
    # True = GPT-J interleaved pairs (rotate_every_two); False = GPT-NeoX
    # half-split (rotate_half)
    rotary_interleaved: bool = True
    parallel_residual: bool = False  # GPT-J: x + attn(ln(x)) + mlp(ln(x))
    # GPT-NeoX: parallel residual with a SEPARATE ln2 feeding the MLP branch:
    # x + attn(ln1(x)) + mlp(ln2(x))
    parallel_residual_dual_ln: bool = False
    post_ln: bool = False          # BERT: LayerNorm AFTER each residual add
    embed_ln: bool = False         # BLOOM/BERT: LayerNorm on the embeddings
    token_type_vocab: int = 0      # BERT segment embeddings
    mlm_head: bool = False         # BERT: transform (dense+act+LN) + decoder bias
    lm_head_bias: bool = False     # GPT-J: untied lm_head carries a bias
    # no LM head at all: __call__ returns final hidden states [B, S, H]
    # (CLIP text encoder; reference: module_inject CLIP policy)
    no_lm_head: bool = False
    qkv_bias: Optional[bool] = None       # None = use_bias (GPT-Neo/J: False)
    attn_out_bias: Optional[bool] = None  # None = use_bias (GPT-J: False)
    # per-layer local attention window, 0 = global (GPT-Neo alternates 0/256)
    layer_windows: Optional[Tuple[int, ...]] = None
    # random-LTD (reference: data_pipeline/data_routing + csrc/random_ltd):
    # layers in [ltd_start, ltd_end) process only ltd_tokens randomly-sampled
    # tokens per step; the rest pass through on the residual. Requires
    # scan_layers=False (the token subset changes the layer's shapes).
    ltd_tokens: int = 0
    ltd_start: int = 0
    ltd_end: int = 0
    # progressive layer drop (reference: runtime/progressive_layer_drop.py):
    # keep layer l with prob 1 - (l/L)(1-theta); theta arrives per step via
    # the "pld_theta" batch key (so no recompile as the schedule moves)
    pld: bool = False
    # -- modern-decoder knobs (Llama/Mistral family — post-dates the
    #    reference v0.8.1; exceeds its policy list) ---------------------------
    norm: str = "layernorm"        # "rmsnorm": no-mean, no-bias (Llama)
    # SwiGLU MLP: down(silu(gate(x)) * up(x)) — three matmuls; activation
    # field selects the gate nonlinearity ("silu" for Llama)
    gated_mlp: bool = False
    # grouped-query attention: k/v heads < q heads, repeated at attention
    # (None = MHA). num_heads % num_kv_heads must be 0.
    num_kv_heads: Optional[int] = None
    rope_theta: float = 10000.0    # rotary base (Llama-3 uses 500000)
    # scaled RoPE (HF config.rope_scaling; llama3 per-frequency remap /
    # linear position interpolation / dynamic NTK). All parameters are
    # trace-time static, so the scaled inv_freq table costs nothing at run
    # time. HF formula sources: transformers modeling_rope_utils
    # _compute_{linear_scaling,dynamic_ntk,llama3}_parameters.
    rope_scaling_type: Optional[str] = None   # "linear"|"dynamic"|"llama3"
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0         # llama3 only
    rope_high_freq_factor: float = 4.0        # llama3 only
    rope_original_max_position: int = 0       # 0 = max_seq_len
    # decoupled head_dim (Mistral-Nemo/Gemma style): attention head width
    # independent of hidden_size/num_heads; qkv projects to
    # (nh + 2*kv) * head_dim and attn_proj maps nh*head_dim back to H
    head_dim_override: Optional[int] = None
    # biases on the gated-MLP projections (HF LlamaConfig.mlp_bias);
    # None = use_bias
    mlp_bias: Optional[bool] = None
    # Qwen3: per-head RMSNorm on q and k (over head_dim) before rotary
    qk_norm: bool = False
    # Gemma: token embeddings scaled by sqrt(hidden_size), applied in the
    # COMPUTE dtype (HF casts the normalizer to the hidden dtype, so bf16
    # runs see the same rounding)
    embed_scale: Optional[float] = None
    # Gemma-2 "sandwich" norms: each branch output is normed AGAIN before
    # its residual add (post_attn_norm / post_mlp_norm; the pre-MLP norm
    # keeps the ln2 slot)
    post_block_norms: bool = False
    # Gemma-2 logit softcapping: tanh(x/cap)*cap on attention scores
    # (applied IN-KERNEL on the Pallas flash path; exact reference impl
    # elsewhere) and on the final LM logits; 0 = off
    attn_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    # explicit MLP width when it is not ratio*H (Llama: 11008 at H=4096)
    mlp_dim_override: Optional[int] = None
    # MoE (reference: deepspeed/moe/*): >0 replaces every block's MLP with a
    # mixture of moe_experts experts; aux loss returned next to the logits
    moe_experts: int = 0
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # block-sparse attention layout (ds_config "sparse_attention" section;
    # the engine wires it here and sets attention_impl="sparse"): a hashable
    # tuple of (key, value) items — lists as tuples — so the frozen config
    # stays usable as a jit static argument. Keys mirror
    # config.SparseAttentionConfig ("mode", "block", "num_local_blocks", ...).
    sparse_attention: Optional[Tuple[Tuple[str, Any], ...]] = None
    # round-17 low-precision training EXPERIMENT (not a default): "int8"
    # or "fp8" fake-quantizes every block matmul input (straight-through
    # gradients, quant_format.fake_quant_act) — emulated low-precision
    # compute numerics at full-precision speed. The engine wires it from
    # compression_training.activation_quantization and REQUIRES the
    # integrity sentinel's skip/rollback ladder to be armed.
    activation_quant: Optional[str] = None

    def __post_init__(self):
        # gated_mlp + moe_experts is the Mixtral family: SwiGLU experts
        # (moe/layer.GatedExpertMLP); the 3-matmul count flows through
        # _mlp_params so the 6N accounting stays honest
        if self.post_block_norms and self.parallel_residual:
            # the parallel-residual paths return before the sandwich
            # norms; silently skipping them would diverge train vs decode
            raise NotImplementedError(
                "post_block_norms (Gemma-2 sandwich) + parallel_residual "
                "is not implemented")
        if self.activation_quant not in (None, "int8", "fp8"):
            raise ValueError(
                f"activation_quant {self.activation_quant!r}: expected "
                "'int8', 'fp8' or None")

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_heads

    def uniform_window(self) -> Optional[int]:
        """The single static window every layer shares, when layer_windows
        is uniform: 0 for no/global windows, the window size otherwise;
        None when layers MIX windows (per-layer routing must stay dynamic).
        Shared by the training path (keeps the window static under nn.scan)
        and the generation prefill (flash-kernel eligibility)."""
        if self.layer_windows is None:
            return 0
        vals = {max(int(w), 0) for w in self.layer_windows}
        return vals.pop() if len(vals) == 1 else None

    def rope_inv_freq(self, seq_len: Optional[int] = None):
        """Static inverse-frequency table for rotary embeddings with the
        configured rope_scaling applied (mirrors HF modeling_rope_utils for
        linear / dynamic / llama3). Returns None when no scaling is
        configured so apply_rotary keeps its original in-trace table —
        bit-identical to what every unscaled arch's token-exact parity was
        validated against.

        ``seq_len``: the target length the table must cover — dynamic NTK
        stretches the base once this exceeds the original window (HF
        recomputes per forward from max(position)+1; passing the static
        trace-time S here matches that exactly). Decode passes the cache
        capacity instead: one table for the whole planned generation,
        where HF re-rotates nothing and lets keys cached under earlier
        tables disagree — ours is the path-independent variant."""
        t = self.rope_scaling_type
        if t is None or t == "default":
            return None
        # float32 arithmetic end-to-end: HF computes these tables in
        # torch.float32, and parity is checked token-exact
        rd = self.rotary_dim or self.head_dim
        inv = 1.0 / (self.rope_theta ** (np.arange(0, rd, 2,
                                                   dtype=np.float32) / rd))
        f = self.rope_scaling_factor
        orig = self.rope_original_max_position or self.max_seq_len
        if t == "linear":
            inv = inv / f
        elif t == "dynamic":
            # NTK: the base stretches once positions exceed the original
            # window; seq_len is static under jit, so the table for
            # max_seq_len is the one HF would have converged to at that
            # length (identical to default while max_seq_len <= orig)
            eff = max(seq_len or self.max_seq_len, orig)
            base = self.rope_theta * (
                (f * eff / orig) - (f - 1)) ** (rd / (rd - 2))
            inv = 1.0 / (base ** (np.arange(0, rd, 2,
                                            dtype=np.float32) / rd))
        elif t == "llama3":
            lo, hi = self.rope_low_freq_factor, self.rope_high_freq_factor
            low_wl, high_wl = orig / lo, orig / hi
            wavelen = 2.0 * np.pi / inv
            inv_l = np.where(wavelen > low_wl, inv / f, inv)
            smooth = (orig / wavelen - lo) / (hi - lo)
            smoothed = (1.0 - smooth) * inv_l / f + smooth * inv_l
            is_medium = (wavelen >= high_wl) & (wavelen <= low_wl)
            inv = np.where(is_medium, smoothed, inv_l)
        else:
            raise NotImplementedError(
                f"rope_scaling type {t!r} is not implemented "
                "(yarn / longrope are out of scope)")
        return inv.astype(np.float32)

    @property
    def mlp_dim(self) -> int:
        if self.mlp_dim_override is not None:
            return self.mlp_dim_override
        return self.hidden_size * self.mlp_ratio

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def _embed_params(self) -> int:
        h, v = self.hidden_size, self.vocab_size
        n = v * h
        if self.pos_embed == "learned":
            n += self.max_seq_len * h
        if not self.tie_embeddings and not self.no_lm_head:
            n += v * h                        # untied lm_head
        return n

    def _attn_params(self) -> int:
        h = self.hidden_size
        return (self.num_heads + 2 * self.kv_heads) * self.head_dim * h \
            + self.num_heads * self.head_dim * h   # qkv (GQA) + out proj

    def _mlp_params(self) -> int:
        return (3 if self.gated_mlp else 2) * self.mlp_dim * self.hidden_size

    def num_params(self) -> int:
        per_layer = self._attn_params() \
            + self._mlp_params() * max(self.moe_experts, 1)
        if self.moe_experts > 0:
            per_layer += self.hidden_size * self.moe_experts  # router
        return self._embed_params() + self.num_layers * per_layer

    def num_active_params(self) -> int:
        """Params touched per token (== num_params for dense; MoE routes each
        token through moe_k of moe_experts expert MLPs). This is the N that
        belongs in the 6N FLOPs-per-token model."""
        if self.moe_experts <= 0:
            return self.num_params()
        per_layer = (self._attn_params() + self._mlp_params() * self.moe_k
                     + self.hidden_size * self.moe_experts)
        return self._embed_params() + self.num_layers * per_layer

    # -- tensor-parallel sharding rules (regex on param path -> PartitionSpec) --
    def tp_rules(self) -> Dict[str, P]:
        """Megatron-style TP over the 'model' mesh axis.

        Scanned layers carry a leading layer dim, so block-param specs lead
        with None. Column-parallel: qkv & fc1 (output dim sharded);
        row-parallel: attn proj & fc2 (input dim sharded); embedding is
        vocab-parallel (reference inference TP slices the same way:
        module_inject/replace_policy.py).
        """
        # scanned layers live under "blocks/..." with a leading layer dim;
        # unrolled layers are "blocks_<i>/..." without it
        lead = (None,) if self.scan_layers else ()
        prefix = r"blocks/" if self.scan_layers else r"blocks_\d+/"

        def block(spec):
            return P(*(lead + spec))

        return {
            prefix + r".*attn_qkv/kernel": block((None, "model")),
            prefix + r".*attn_qkv/bias": block(("model",)),
            prefix + r".*attn_proj/kernel": block(("model", None)),
            prefix + r".*mlp_fc/kernel": block((None, "model")),
            prefix + r".*mlp_fc/bias": block(("model",)),
            prefix + r".*mlp_gate/kernel": block((None, "model")),
            prefix + r".*mlp_gate/bias": block(("model",)),
            prefix + r".*mlp_proj/kernel": block(("model", None)),
            r"wte/embedding": P("model", None),
            r"lm_head/kernel": P(None, "model"),
            # MoE expert stacks: [.., E, in, out] — expert axis + row/col TP
            # (gate = the SwiGLU expert's column-parallel gate projection,
            # Mixtral family; the ROUTER at moe/gate is deliberately
            # unmatched — _Gate pins it replicated)
            prefix + r".*experts/fc/kernel": block(("expert", None, "model")),
            prefix + r".*experts/fc/bias": block(("expert", "model")),
            prefix + r".*experts/gate/kernel": block(("expert", None,
                                                      "model")),
            prefix + r".*experts/gate/bias": block(("expert", "model")),
            prefix + r".*experts/proj/kernel": block(("expert", "model", None)),
            prefix + r".*experts/proj/bias": block(("expert", None)),
        }


# -- presets (sizes follow the reference's BASELINE ladder: GPT-2 125M→6.7B,
#    BERT base/large; docs/_pages/training.md) --------------------------------
_PRESETS = {
    "gpt2-tiny": dict(hidden_size=128, num_layers=2, num_heads=4, vocab_size=1024,
                      max_seq_len=256),
    "gpt2-125m": dict(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-350m": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-760m": dict(hidden_size=1536, num_layers=24, num_heads=16),
    "gpt2-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16),
    "gpt2-2.7b": dict(hidden_size=2560, num_layers=32, num_heads=32),
    "gpt2-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32),
    # TinyLlama-1.1B shapes: the modern-decoder leg (RMSNorm + SwiGLU +
    # GQA 32q/4kv + rotary) of the perf table
    "llama-1.1b": dict(hidden_size=2048, num_layers=22, num_heads=32,
                       num_kv_heads=4, mlp_dim_override=5632,
                       norm="rmsnorm", gated_mlp=True, activation="silu",
                       pos_embed="rotary", rotary_interleaved=False,
                       use_bias=False, tie_embeddings=False,
                       vocab_size=32000, max_seq_len=2048),
    "bert-base": dict(hidden_size=768, num_layers=12, num_heads=12, causal=False,
                      vocab_size=30522, max_seq_len=512),
    "bert-large": dict(hidden_size=1024, num_layers=24, num_heads=16, causal=False,
                       vocab_size=30522, max_seq_len=512),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    if name not in _PRESETS:
        raise ValueError(f"unknown preset '{name}'; have {sorted(_PRESETS)}")
    kw = dict(_PRESETS[name])
    kw.update(overrides)
    return TransformerConfig(**kw)


_ACTIVATIONS = {
    "gelu": nn.gelu,                                    # tanh approximation
    "gelu_exact": lambda x: nn.gelu(x, approximate=False),
    "relu": nn.relu,
    "quick_gelu": lambda x: x * nn.sigmoid(1.702 * x),  # CLIP
    "silu": nn.silu,                                    # Llama SwiGLU gate
}


def apply_rotary(x: jnp.ndarray, positions: jnp.ndarray,
                 rotary_dim: int = 0, interleaved: bool = True,
                 theta: float = 10000.0, inv_freq=None) -> jnp.ndarray:
    """Rotary embedding; interleaved=True is the GPT-J rotate_every_two pair
    layout, False is the GPT-NeoX rotate_half half-split layout.

    x: [B, nh, S, hd]; positions: [B, S] or [S]. Only the first rotary_dim
    channels rotate (GPT-J: 64 of 256; NeoX: rotary_pct * hd); the rest pass
    through. ``inv_freq`` (a static [rd/2] table, e.g. from
    TransformerConfig.rope_inv_freq for scaled-RoPE variants) overrides the
    plain-theta table. reference arch sources: HF
    GPTJAttention._apply_rotary_pos_emb, HF GPTNeoXAttention (rotate_half).
    """
    B, nh, S, hd = x.shape
    rd = rotary_dim or hd
    if positions.ndim == 1:
        positions = positions[None, :]
    if inv_freq is None:
        inv_freq = 1.0 / (theta ** (jnp.arange(0, rd, 2) / rd))
    else:
        inv_freq = jnp.asarray(inv_freq, jnp.float32)
    ang = positions[:, :, None].astype(jnp.float32) * inv_freq[None, None, :]
    sin = jnp.sin(ang)[:, None, :, :]                   # [B, 1, S, rd/2]
    cos = jnp.cos(ang)[:, None, :, :]
    xr = x[..., :rd].astype(jnp.float32)
    if interleaved:
        x1 = xr[..., 0::2]
        x2 = xr[..., 1::2]
        rot1 = x1 * cos - x2 * sin
        rot2 = x2 * cos + x1 * sin
        rot = jnp.stack([rot1, rot2], axis=-1).reshape(B, nh, S, rd)
    else:
        x1 = xr[..., :rd // 2]
        x2 = xr[..., rd // 2:]
        rot = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                              axis=-1)
    return jnp.concatenate([rot.astype(x.dtype), x[..., rd:]], axis=-1)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """ALiBi per-head slopes (BLOOM; HF build_alibi_tensor formula)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))
    if np.log2(num_heads).is_integer():
        return pow2_slopes(num_heads)
    base = 2 ** int(np.floor(np.log2(num_heads)))
    extra = pow2_slopes(2 * base)[0::2][:num_heads - base]
    return np.concatenate([pow2_slopes(base), extra])


def alibi_bias(num_heads: int, q_pos: jnp.ndarray, k_pos: jnp.ndarray
               ) -> jnp.ndarray:
    """Additive bias -slope * (q - k): [B, H, Sq, Sk] for [B, S] positions
    (packed/per-sample position ids), [1, H, Sq, Sk] for shared [S]."""
    slopes = jnp.asarray(alibi_slopes(num_heads), jnp.float32)
    if q_pos.ndim == 1:
        q_pos, k_pos = q_pos[None], k_pos[None]
    dist = (k_pos[:, None, :] - q_pos[:, :, None]).astype(jnp.float32)
    return slopes[None, :, None, None] * dist[:, None]


def _sparse_block_attention(cfg, q, k, v, *, mask, bias, slopes, window,
                            sm_scale, dropout_rate, dropout_rng):
    """attention_impl == "sparse": execute the ds_config-selected block-sparse
    layout (engine wires the parsed section into cfg.sparse_attention).

    Clean calls (no mask/bias/dropout/softcap/window) run the Pallas
    layout-skip kernel via ops.sparse_attention.sparse_attention — FLOPs
    scale with layout density. Anything extra composes the layout into a
    dense mask over the exact jnp reference instead: the configured sparsity
    is still honored bit-exactly, only the FLOP scaling is lost. Unknown
    modes raise here (and in the engine wiring) — never silently dense.
    """
    import dataclasses as _dc

    from ..ops.attention import alibi_bias_from_slopes, mha_reference
    from ..ops.sparse_attention import (SPARSITY_CONFIGS, layout_to_dense_mask,
                                        sparse_attention)
    B, H, S, D = q.shape
    kwargs = {key: (list(val) if isinstance(val, tuple) else val)
              for key, val in (cfg.sparse_attention or ())}
    mode = kwargs.pop("mode", "fixed")
    if mode not in SPARSITY_CONFIGS:
        raise ValueError(f"unknown sparse attention mode '{mode}'; "
                         f"have {sorted(SPARSITY_CONFIGS)}")
    cls = SPARSITY_CONFIGS[mode]
    allowed = {f.name for f in _dc.fields(cls)} - {"num_heads"}
    sp_cfg = cls(num_heads=H,
                 **{key: val for key, val in kwargs.items()
                    if key in allowed and val is not None})
    clean = (mask is None and bias is None and slopes is None
             and dropout_rate == 0.0 and not window and not cfg.attn_softcap)
    if clean:
        return sparse_attention(q, k, v, sp_cfg, causal=cfg.causal,
                                sm_scale=sm_scale)
    if slopes is not None:
        bias = alibi_bias_from_slopes(slopes, S, S)
    lmask = layout_to_dense_mask(sp_cfg.make_layout(S), sp_cfg.block)[None]
    mask = lmask if mask is None else mask & lmask
    if window:
        from ..ops.attention import window_mask
        mask = mask & window_mask(S, S, window)
    return mha_reference(q, k, v, causal=cfg.causal, bias=bias, mask=mask,
                         sm_scale=sm_scale, dropout_rate=dropout_rate,
                         dropout_rng=dropout_rng, softcap=cfg.attn_softcap)


def _spec_constraint(x, spec: P):
    """Sharding constraint that works both under plain ``jax.jit`` and
    inside a shard_map.

    Under plain jit there is no ambient mesh, so a bare PartitionSpec would
    raise — and the round-3 try/except silently swallowed that, leaving
    activation layouts to partitioner inference (the involuntary-remat
    warnings). There the spec is resolved against the session's global mesh
    into a NamedSharding. Inside a shard_map (e.g. the pipeline executor's
    Manual-'pipe' context) a full-mesh NamedSharding is REJECTED — there the
    bare spec is exactly right: it resolves against the context mesh and
    ignores the manual axes (our specs never name 'pipe')."""
    # the comm-plan stacked-grads step traces the model SHARD-LOCALLY
    # (manual over the DP axes): every mesh constraint is meaningless
    # there — and naming a manual axis in one is an error on jax lines
    # without the abstract-mesh probe below — so the local-region flag
    # turns them all off for that trace. The TP-composed stacked step
    # (round 14) instead passes its manual-axes set: entries naming a
    # manual axis are stripped, the surviving TP entries resolve against
    # the partial-auto region's context mesh.
    from ..comm_plan.runtime import in_local_region, local_region_manual_axes
    if in_local_region():
        manual = local_region_manual_axes()
        if manual is None:
            return x
        filtered = []
        for entry in spec:
            if entry is None:
                filtered.append(None)
                continue
            names = tuple(n for n in
                          ((entry,) if isinstance(entry, str) else entry)
                          if n not in manual)
            filtered.append(None if not names
                            else names[0] if len(names) == 1 else names)
        if not any(e is not None for e in filtered):
            return x
        return jax.lax.with_sharding_constraint(x, P(*filtered))
    # jax-version compat: get_abstract_mesh moved under jax.sharding only in
    # newer releases; older trees keep it in jax._src.mesh (and lack
    # sharding-in-types entirely — see the typeof probe below)
    _get_ctx = getattr(jax.sharding, "get_abstract_mesh", None)
    ctx = _get_ctx() if _get_ctx is not None else None
    # old jax: no public accessor (jax._src.mesh's same-named thread-local
    # has different semantics) — getattr below treats ctx as absent
    if getattr(ctx, "empty", None) is False:
        return jax.lax.with_sharding_constraint(x, spec)
    from ..parallel.mesh import get_global_mesh
    mm = get_global_mesh()
    if mm is None:
        return x                       # plain CPU tests: no mesh, no layout
    # scope check (sharding-in-types): activations of a computation whose
    # inputs are laid out on a mesh carry that mesh in their aval; a plain
    # -jit call on single-device/committed-elsewhere data carries an EMPTY
    # abstract mesh, and pinning IT to the session mesh would be a device
    # -scope error — exactly the ad-hoc case (profiler init, one-device
    # side computation) that must run unconstrained. The flip side of the
    # contract: a program gets mesh layouts only when its INPUTS are
    # placed on the mesh (engine APIs do this; raw jit over uncommitted
    # arrays runs unconstrained — device_put params/batch with a
    # NamedSharding to opt in). Engine init traces run uncommitted and
    # intentionally skip constraints: param placement comes from the init
    # jit's out_shardings, not activation constraints.
    # programs placing inputs via jit(in_shardings=...) ALSO trace with an
    # empty aval mesh (verified on jax 0.9) and would skip constraints;
    # DSTPU_FORCE_MESH_CONSTRAINTS=1 restores the always-constrain
    # behavior for that idiom (documented in docs/USAGE.md)
    import os
    _typeof = getattr(jax, "typeof", None)
    if os.environ.get("DSTPU_FORCE_MESH_CONSTRAINTS") != "1" \
            and _typeof is not None:
        # jax without sharding-in-types (no typeof) predates the empty-aval
        # -mesh scope hazard: constrain unconditionally there
        aval_mesh = getattr(getattr(_typeof(x), "sharding", None),
                            "mesh", None)
        if aval_mesh is None or getattr(aval_mesh, "empty", False):
            return x
    # a computation not laid out on the session mesh (e.g. a smaller
    # ad-hoc batch) can't take the constraint — detectable as
    # non-divisible sharded dims
    for dim, entry in enumerate(spec[:np.ndim(x)]):
        if entry is None or entry is P.UNCONSTRAINED:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        size = 1
        for a in axes:
            size *= mm.shape.get(a, 1)
        if size and np.shape(x)[dim] % size != 0:
            return x
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(x, NamedSharding(mm.mesh, spec))


def _batch_constraint(x):
    """Constrain activations [B, S, H] to the mesh's batch/seq layout (H
    left to the partitioner)."""
    return _spec_constraint(
        x, P(("data", "expert"), "seq", P.UNCONSTRAINED))


class _TDense(nn.Module):
    """nn.Dense (same param names/init, drop-in) whose kernel read is pinned
    to its gathered, TP-only layout.

    Under ZeRO-3 the stacked kernels arrive sharded over the ZeRO axes on
    their contraction dim; left to inference, the partitioner computes the
    backward's dx = dy @ W^T with W still sharded and emits dx H-sharded —
    clashing with the batch/seq activation layout at the backward scan
    boundary (the round-3 'involuntary full rematerialization' warnings).
    Pinning the kernel read makes the ZeRO-3 gather-on-use explicit in
    forward AND (via the constraint's transpose) backward, so dx stays in
    batch layout and the dW cotangent resharding lowers to the usual
    reduce-scatter. The reference's analogue is the stage-3 allgather in
    both passes (partitioned_param_coordinator.fetch_sub_module)."""
    features: int
    kernel_spec: Optional[Tuple] = None
    use_bias: bool = True
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), self.param_dtype)
        bias = (self.param("bias", nn.initializers.zeros_init(),
                           (self.features,), self.param_dtype)
                if self.use_bias else None)
        if self.kernel_spec is not None:
            kernel = _spec_constraint(kernel, P(*self.kernel_spec))
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        if bias is not None:
            y = y + bias.astype(self.dtype)
        return y


class Block(nn.Module):
    """One transformer block (attention + MLP).

    Default is the pre-LN GPT shape; cfg knobs reconfigure it into the other
    policy architectures: post_ln (BERT), parallel_residual (GPT-J), rotary /
    alibi positions, per-layer local windows (GPT-Neo), activations.
    """
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, train: bool = False, window=None,
                 positions=None):
        cfg = self.cfg
        # entry constraint pairs with the exit constraints below: its
        # TRANSPOSE pins the block-input cotangent — the backward layer-scan
        # carry — to the same batch/seq layout. Without it the partitioner
        # may pick a contraction-dim (H) sharding for dx inside the backward
        # while-loop and pay an involuntary replicate-and-reshard at every
        # iteration (the last two spmd_partitioner warnings of round 3).
        x = _batch_constraint(x)
        B, S, H = x.shape
        nh, hd = cfg.num_heads, cfg.head_dim
        act = _ACTIVATIONS[cfg.activation]
        # TP-only (gathered) kernel layouts by name — the ZeRO axes are
        # deliberately absent: _TDense pins the kernel READ to this spec
        _KSPEC = {"attn_qkv": (None, "model"), "attn_proj": ("model", None),
                  "mlp_fc": (None, "model"), "mlp_gate": (None, "model"),
                  "mlp_proj": ("model", None)}
        _mk_dense = lambda feats, name, bias=None: _TDense(
            feats, kernel_spec=_KSPEC.get(name),
            use_bias=cfg.use_bias if bias is None else bias,
            dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        if cfg.activation_quant is None:
            dense = _mk_dense
        else:
            # round-17 low-precision experiment: every block matmul sees
            # an int8/fp8-rounded INPUT (straight-through gradient) — the
            # module is built eagerly so the flax param order is identical
            # to the unquantized block (checkpoints interchange freely)
            from ..quant_format import fake_quant_act
            dense = lambda feats, name, bias=None: (
                lambda h, _m=_mk_dense(feats, name, bias): _m(
                    fake_quant_act(h, cfg.activation_quant)))
        if cfg.norm == "rmsnorm":
            ln = lambda name: nn.RMSNorm(epsilon=cfg.layer_norm_eps,
                                         dtype=cfg.dtype,
                                         param_dtype=jnp.float32, name=name)
        else:
            ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                           dtype=cfg.dtype,
                                           param_dtype=jnp.float32, name=name)

        # attention ----------------------------------------------------------
        kv = cfg.kv_heads
        if nh % kv != 0:
            raise ValueError(f"num_heads {nh} not divisible by "
                             f"num_kv_heads {kv}")
        h = x if cfg.post_ln else ln("ln1")(x)
        # one fused qkv matmul even under GQA: [H, (nh + 2*kv) * hd]
        qkv = dense((nh + 2 * kv) * hd, "attn_qkv", bias=cfg.qkv_bias)(h)
        q, k, v = jnp.split(qkv, [nh * hd, (nh + kv) * hd], axis=-1)
        to_heads = lambda t, n: t.reshape(B, S, n, hd).transpose(0, 2, 1, 3)
        q, k, v = to_heads(q, nh), to_heads(k, kv), to_heads(v, kv)
        if cfg.qk_norm:
            # Qwen3: RMSNorm over head_dim on q/k, before rotary (HF
            # Qwen3Attention.q_norm/k_norm — per-head, scale-only)
            qk_ln = lambda name: nn.RMSNorm(
                epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                param_dtype=jnp.float32, name=name)
            q = qk_ln("q_norm")(q)
            k = qk_ln("k_norm")(k)
        if cfg.pos_embed == "rotary":
            pos = positions if positions is not None else jnp.arange(S)
            inv_freq = cfg.rope_inv_freq(S)     # None = plain-theta table
            q = apply_rotary(q, pos, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
            k = apply_rotary(k, pos, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
        if kv != nh:
            # grouped-query: each k/v head serves nh/kv query heads
            k = jnp.repeat(k, nh // kv, axis=1)
            v = jnp.repeat(v, nh // kv, axis=1)
        bias = None
        slopes = None
        if cfg.pos_embed == "alibi":
            if positions is None:
                # default arange positions: pass the per-head slopes so the
                # flash kernel rebuilds the bias from block indices — no
                # [B, H, S, S] materialization on the kernel path
                slopes = jnp.asarray(alibi_slopes(nh), jnp.float32)
            else:
                # packed / per-sample position ids: the distance matrix is
                # genuinely data-dependent, materialize it
                bias = alibi_bias(nh, positions, positions)
        mask = attn_mask
        win = 0
        if window is not None:
            # local sliding window (GPT-Neo): q attends k in (q-window, q].
            # attention() routes this to the block-skip sliding-window kernel
            # on TPU (compute scales with the window); with a user mask or
            # under tracing where `window` is dynamic, it composes into the
            # dense mask (exact either way)
            if isinstance(window, (int, np.integer)):
                win = max(int(window), 0)          # <=0 means global
            else:
                q_pos = jnp.arange(S)[:, None]
                k_pos = jnp.arange(S)[None, :]
                wmask = (q_pos - k_pos < window) | (window <= 0)
                mask = (wmask[None, None] if mask is None
                        else mask & wmask[None, None])
        drop_rng = (self.make_rng("dropout")
                    if train and cfg.dropout > 0.0 else None)
        if cfg.attention_impl == "sparse":
            out = _sparse_block_attention(
                cfg, q, k, v, mask=mask, bias=bias, slopes=slopes,
                window=win, sm_scale=cfg.attn_scale,
                dropout_rate=cfg.dropout if train else 0.0,
                dropout_rng=drop_rng)
        else:
            out = attention(q, k, v, causal=cfg.causal, mask=mask, bias=bias,
                            alibi_slopes=slopes, sm_scale=cfg.attn_scale,
                            dropout_rate=cfg.dropout if train else 0.0,
                            dropout_rng=drop_rng, impl=cfg.attention_impl,
                            window=win, softcap=cfg.attn_softcap)
        # tag so the "dots" remat policy keeps it: the Pallas kernel output is
        # not a dot_general, and recomputing flash fwd in bwd costs ~2ms/layer
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "attn_out")
        # nh*hd == H unless head_dim_override decouples them (Mistral-Nemo)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
        out = dense(H, "attn_proj", bias=cfg.attn_out_bias)(out)
        if cfg.dropout > 0.0 and train:
            out = nn.Dropout(cfg.dropout)(out, deterministic=False)

        aux = jnp.zeros((), jnp.float32)

        def mlp(h):
            if cfg.moe_experts > 0:
                from ..moe.layer import ExpertMLP, GatedExpertMLP, MoE
                if cfg.gated_mlp:
                    # Mixtral family: SwiGLU experts
                    make_expert = lambda: GatedExpertMLP(
                        H, cfg.mlp_dim, dtype=cfg.dtype,
                        use_bias=cfg.use_bias, activation=cfg.activation,
                        name="experts")
                else:
                    make_expert = lambda: ExpertMLP(
                        H, cfg.mlp_dim, dtype=cfg.dtype,
                        use_bias=cfg.use_bias, name="experts")
                return MoE(
                    hidden_size=H,
                    num_experts=cfg.moe_experts,
                    expert=make_expert,
                    k=cfg.moe_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    eval_capacity_factor=cfg.moe_capacity_factor,
                    dtype=cfg.dtype,
                    name="moe")(h, train=train)
            if cfg.gated_mlp:
                # SwiGLU (Llama family): down(act(gate(x)) * up(x)); the
                # gate/up matmuls fuse side by side on the MXU
                g = act(dense(cfg.mlp_dim, "mlp_gate", bias=cfg.mlp_bias)(h))
                h = g * dense(cfg.mlp_dim, "mlp_fc", bias=cfg.mlp_bias)(h)
                return dense(H, "mlp_proj", bias=cfg.mlp_bias)(h), aux
            h = dense(cfg.mlp_dim, "mlp_fc")(h)
            h = act(h)
            h = dense(H, "mlp_proj")(h)
            return h, aux

        if cfg.parallel_residual:
            # GPT-J: one shared LN feeds both branches; GPT-NeoX: a separate
            # ln2 feeds the MLP branch. Single residual add either way.
            m, aux = mlp(ln("ln2")(x) if cfg.parallel_residual_dual_ln else h)
            if cfg.dropout > 0.0 and train:
                m = nn.Dropout(cfg.dropout)(m, deterministic=False)
            return _batch_constraint(x + out + m), aux

        if cfg.post_ln:
            # BERT: LN after each residual add
            x = ln("ln1")(x + out)
            m, aux = mlp(x)
            if cfg.dropout > 0.0 and train:
                m = nn.Dropout(cfg.dropout)(m, deterministic=False)
            return _batch_constraint(ln("ln2")(x + m)), aux

        if cfg.post_block_norms:
            # Gemma-2 sandwich: norm each branch OUTPUT before its residual
            out = ln("post_attn_norm")(out)
        x = _batch_constraint(x + out)
        m, aux = mlp(ln("ln2")(x))
        if cfg.post_block_norms:
            m = ln("post_mlp_norm")(m)
        if cfg.dropout > 0.0 and train:
            m = nn.Dropout(cfg.dropout)(m, deterministic=False)
        return _batch_constraint(x + m), aux


class Transformer(nn.Module):
    """GPT-style LM (causal=True) or BERT-style encoder (causal=False)."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, batch, train: bool = False):
        cfg = self.cfg
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            attention_mask = batch.get("attention_mask")
            position_ids = batch.get("position_ids")
        else:
            input_ids, attention_mask, position_ids = batch, None, None
        B, S = input_ids.shape

        if cfg.ltd_tokens > 0 and cfg.scan_layers:
            raise ValueError("random-LTD needs scan_layers=False (the token "
                             "subset changes layer shapes per depth)")
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="wte")
        # blocks receive the USER's position_ids only (None for the default
        # arange): rotary rebuilds arange internally, and alibi with default
        # positions rides the flash kernel's slope path instead of a
        # materialized [B, H, S, S] bias
        user_positions = position_ids
        if position_ids is None:
            position_ids = jnp.arange(S)[None, :]
        x = wte(input_ids)
        if cfg.embed_scale is not None:
            x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
        if cfg.pos_embed == "learned":
            wpe = nn.Embed(cfg.max_seq_len, cfg.hidden_size, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="wpe")
            x = x + wpe(position_ids)
        if cfg.token_type_vocab > 0:
            tte = nn.Embed(cfg.token_type_vocab, cfg.hidden_size,
                           dtype=cfg.dtype, param_dtype=jnp.float32,
                           name="tte")
            token_type_ids = (batch.get("token_type_ids")
                              if isinstance(batch, dict) else None)
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + tte(token_type_ids)
        if cfg.embed_ln:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=jnp.float32, name="ln_emb")(x)
        if cfg.dropout > 0.0 and train:
            x = nn.Dropout(cfg.dropout)(x, deterministic=False)
        x = _batch_constraint(x)

        # padding mask [B, 1, 1, S] broadcast over heads and query positions
        attn_mask = (attention_mask[:, None, None, :].astype(bool)
                     if attention_mask is not None else None)

        block = Block
        if cfg.remat:
            policies = {
                "full": jax.checkpoint_policies.nothing_saveable,
                "dots": jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names("attn_out")),
                # leanest useful set: keep ONLY the flash-attention outputs
                # (recomputing flash fwd in bwd is the one expensive recompute)
                # and re-run qkv/mlp matmuls from the layer input — activation
                # memory per layer drops ~10x vs "dots", buying micro-batch
                "attn": jax.checkpoint_policies.save_only_these_names(
                    "attn_out"),
            }
            # CPU activation checkpointing (reference: checkpointing.py
            # cpu_checkpointing — saved activations live in host memory):
            # offload the attention outputs to pinned host, recompute the rest
            if hasattr(jax.checkpoint_policies,
                       "save_and_offload_only_these_names"):
                policies["offload"] = \
                    jax.checkpoint_policies.save_and_offload_only_these_names(
                        names_which_can_be_saved=[],
                        names_which_can_be_offloaded=["attn_out"],
                        offload_src="device", offload_dst="pinned_host")
            if cfg.remat_policy not in policies:
                raise ValueError(f"unknown remat_policy '{cfg.remat_policy}'; "
                                 f"have {sorted(policies)}")
            # train AND window are static: a traced window would defeat the
            # sliding-window kernel routing in the unrolled path
            block = nn.remat(Block, static_argnums=(3, 4),
                             policy=policies[cfg.remat_policy])
        # uniform windows (Mistral-class): keep the window a STATIC python
        # int even under nn.scan so attention() can route to the
        # sliding-window / flash kernels; MIXED per-layer windows scan a
        # traced window that can only compose into the dense mask
        uw = cfg.uniform_window()
        static_window = uw or None
        windows = (jnp.asarray(cfg.layer_windows, jnp.int32)
                   if uw is None else None)
        pld_on = cfg.pld and train and self.has_rng("pld")
        theta = jnp.asarray(1.0, jnp.float32)
        if pld_on and isinstance(batch, dict) and \
                batch.get("pld_theta") is not None:
            theta = batch["pld_theta"].reshape(-1)[0].astype(jnp.float32)
        L = cfg.num_layers

        def pld_gate(mdl_rng, carry, out, aux, layer_idx):
            keep_p = 1.0 - ((layer_idx + 1.0) / L) * (1.0 - theta)
            keep = jax.random.bernoulli(mdl_rng, keep_p)
            return (jnp.where(keep, out, carry),
                    jnp.where(keep, aux, 0.0))

        if cfg.scan_layers:
            # the PLD variant threads an extra rng stream + layer index
            # through the scan; keep the plain body when PLD is off — the
            # extra scanned state disturbs the remat policy's saved set
            # (measured ~20% step-time regression on the bench model)
            if pld_on:
                def body(mdl, carry, xs):
                    w, li = xs
                    out, aux = mdl(carry, attn_mask, train,
                                   static_window if w is None else w,
                                   user_positions)
                    out, aux = pld_gate(mdl.make_rng("pld"), carry, out, aux,
                                        li.astype(jnp.float32))
                    return out, aux

                xs = (windows, jnp.arange(L))
                split = {"params": True, "dropout": True, "gating": True,
                         "pld": True}
            else:
                def body(mdl, carry, w):
                    return mdl(carry, attn_mask, train,
                               static_window if w is None else w,
                               user_positions)

                xs = windows
                split = {"params": True, "dropout": True, "gating": True}
            x, auxes = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs=split,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block(cfg, name="blocks"), x, xs)
            aux_total = jnp.sum(auxes)
        else:
            aux_total = jnp.zeros((), jnp.float32)
            ltd_active = (train and cfg.ltd_tokens > 0
                          and cfg.ltd_end > cfg.ltd_start)
            if ltd_active and cfg.layer_windows is not None:
                raise ValueError(
                    "random-LTD + layer_windows is unsupported: the local "
                    "window would apply to compacted subset indices, voiding "
                    "the true token-distance constraint")
            for i in range(cfg.num_layers):
                # static python ints here (unlike the scanned path) so
                # attention() can route to the sliding-window kernel
                w = (int(cfg.layer_windows[i]) or None) \
                    if cfg.layer_windows is not None else None
                blk = block(cfg, name=f"blocks_{i}")
                if pld_on:
                    x_in = x
                if ltd_active and cfg.ltd_start <= i < cfg.ltd_end \
                        and cfg.ltd_tokens < S:
                    # random-LTD: this layer sees only a sampled token subset
                    # (sorted to keep causal order); dropped tokens ride the
                    # residual stream unchanged (reference: random_ltd
                    # gather/scatter kernels, csrc/random_ltd)
                    r = self.make_rng("gating")
                    idx = jnp.sort(jax.random.permutation(
                        jax.random.fold_in(r, i), S)[:cfg.ltd_tokens])
                    x_kept = jnp.take(x, idx, axis=1)
                    mask_kept = (attn_mask[..., idx]
                                 if attn_mask is not None else None)
                    out, aux = blk(x_kept, mask_kept, train, w,
                                   jnp.take(position_ids, idx, axis=1))
                    x = x.at[:, idx].set(out)
                else:
                    x, aux = blk(x, attn_mask, train, w, user_positions)
                if pld_on:
                    x, aux = pld_gate(self.make_rng("pld"), x_in, x, aux,
                                      float(i))
                aux_total = aux_total + aux

        if not cfg.post_ln:
            # post-LN stacks (BERT) end already normalized by each block's ln2
            norm_cls = nn.RMSNorm if cfg.norm == "rmsnorm" else nn.LayerNorm
            x = norm_cls(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="ln_f")(x)
        if cfg.mlm_head:
            # BERT cls.predictions: transform (dense+act+LN) then decoder
            # (tied embedding + output bias)
            h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="mlm_transform")(x)
            h = _ACTIVATIONS[cfg.activation](h)
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=jnp.float32, name="mlm_ln")(h)
            logits = wte.attend(h)
            bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
            return (logits + bias).astype(jnp.float32)
        if cfg.no_lm_head:
            # encoder use (CLIP text): final hidden states are the output
            return x.astype(jnp.float32)
        if cfg.fused_loss:
            if cfg.final_logit_softcap:
                raise ValueError(
                    "fused_loss with final_logit_softcap is not supported "
                    "(the chunked CE has no softcap term); disable "
                    "fused_loss for Gemma-2-class models")
            if cfg.tie_embeddings:
                emb = wte.embedding
            else:
                # untied head (Llama family): declare the SAME lm_head/
                # kernel param the non-fused nn.Dense path creates, so
                # checkpoints and HF imports are layout-identical
                if cfg.lm_head_bias:
                    raise ValueError(
                        "fused_loss with a BIASED untied lm_head is not "
                        "supported (the chunked CE has no bias term)")
                emb = _HeadKernel(cfg.vocab_size, cfg.hidden_size,
                                  name="lm_head")().T
            labels = batch.get("labels", input_ids) if isinstance(batch, dict) \
                else input_ids
            # encoder stacks (BERT bench path) predict in place: no shift
            loss = _fused_causal_lm_loss(x, emb, labels,
                                         cfg.loss_chunk,
                                         shift=1 if cfg.causal else 0)
            if cfg.moe_experts > 0:
                return loss, aux_total
            return loss
        if cfg.tie_embeddings:
            logits = wte.attend(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                              dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if cfg.final_logit_softcap:
            from ..ops.attention import apply_softcap
            logits = apply_softcap(logits, cfg.final_logit_softcap)
        if cfg.moe_experts > 0:
            return logits, aux_total
        return logits


class _HeadKernel(nn.Module):
    """Bare lm_head kernel for the fused-CE path: the param path/shape/init
    match nn.Dense(name="lm_head") exactly, so fused and non-fused models
    share checkpoints."""
    vocab_size: int
    hidden: int

    @nn.compact
    def __call__(self):
        return self.param("kernel", nn.initializers.lecun_normal(),
                          (self.hidden, self.vocab_size), jnp.float32)


def _fused_causal_lm_loss(x, emb, labels, chunk: int, shift: int = 1):
    """Next-token CE without materializing [B, S, V] logits.

    x: [B, S, H] final hidden states (compute dtype); emb: [V, H] fp32 tied
    embedding; labels: [B, S] token ids. The vocab projection runs per
    sequence-chunk under `jax.checkpoint`, so forward AND backward hold at
    most one [B, chunk, V] logits tile; XLA keeps the chunk matmuls on the
    MXU with fp32 accumulation. Replaces the reference's fused CE epilogue
    (csrc/transformer/general_kernels.cu cross-entropy path) the XLA way.
    """
    B, S, H = x.shape
    if shift:
        xs = x[:, :-1]          # causal LM: predict the NEXT token
        tgt = labels[:, 1:]
    else:
        xs, tgt = x, labels     # encoder/MLM-style: predict in place
    n = S - shift
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        xs = jnp.pad(xs, ((0, 0), (0, pad), (0, 0)))
        # -100 padding folds seq padding into the ignore_index mask
        tgt = jnp.pad(tgt, ((0, 0), (0, pad)), constant_values=-100)
    nc = (n + pad) // chunk
    xs = xs.reshape(B, nc, chunk, H).transpose(1, 0, 2, 3)       # [nc,B,C,H]
    tgt = tgt.reshape(B, nc, chunk).transpose(1, 0, 2)           # [nc,B,C]
    emb_c = emb.astype(x.dtype)

    @jax.checkpoint
    def chunk_nll(xc, tc):
        vc = (tc != -100).astype(jnp.float32)        # ignore_index + padding
        safe = jnp.maximum(tc, 0)
        logits = jnp.einsum("bch,vh->bcv", xc, emb_c,
                            preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return jnp.sum((logz - gold) * vc), jnp.sum(vc)

    def body(acc, inp):
        xc, tc = inp
        nll, cnt = chunk_nll(xc, tc)
        return (acc[0] + nll, acc[1] + cnt), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xs, tgt))
    return total / jnp.maximum(count, 1.0)


def fused_loss_passthrough(outputs, batch):
    """Engine loss_fn for models built with fused_loss=True (outputs IS the loss)."""
    return outputs


# ---------------------------------------------------------------------------
# Loss functions (engine `loss_fn` contract: loss_fn(outputs, batch) -> scalar)
# ---------------------------------------------------------------------------

def cross_entropy(logits, labels, ignore_index: int = -100):
    """Token-level CE with ignore mask; fp32 accumulation."""
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def causal_lm_loss(logits, batch):
    """Next-token prediction: shift logits/labels by one."""
    labels = batch.get("labels", batch["input_ids"]) if isinstance(batch, dict) else batch
    return cross_entropy(logits[:, :-1], labels[:, 1:])


def masked_lm_loss(logits, batch):
    """BERT-style: loss only where labels != -100."""
    return cross_entropy(logits, batch["labels"])


def make_moe_loss(aux_weight: float = 0.01, base_loss=None):
    """Loss for MoE models returning (logits, aux): task loss + aux_weight*aux
    (reference: l_aux scaled into the training loss by the client; the engine
    keeps the same contract)."""
    base = base_loss or causal_lm_loss

    def moe_loss(outputs, batch):
        logits, aux = outputs
        return base(logits, batch) + aux_weight * aux

    # marker for schedule dispatch: the 1F1B executor computes the aux term
    # itself (the aux scalar rides the pipe), so the pipe engine must NOT
    # route a moe loss through the per-micro custom-loss path (which would
    # hand it a bare logits array and double-count the aux)
    moe_loss._moe_loss = True
    moe_loss._moe_base_loss = base
    moe_loss._moe_aux_weight = aux_weight
    return moe_loss


def build_model(name_or_cfg, **overrides) -> Tuple[Transformer, TransformerConfig]:
    cfg = (name_or_cfg if isinstance(name_or_cfg, TransformerConfig)
           else get_config(name_or_cfg, **overrides))
    return Transformer(cfg), cfg


class DeepSpeedTransformerLayer(nn.Module):
    """Reference-parity fused transformer layer
    (ops/transformer/transformer.py:459 DeepSpeedTransformerLayer): one
    attention+MLP block applied to [B, S, H] hidden states. On TPU the
    "fused kernels" are XLA fusion + the Pallas attention the Block
    routes to; configure with TransformerConfig (exported under the
    reference's name DeepSpeedTransformerConfig — batch size and seq
    length are runtime shapes here, not config fields)."""
    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 train: bool = False):
        if attention_mask is not None:
            if jnp.issubdtype(jnp.asarray(attention_mask).dtype,
                              jnp.floating):
                # the reference feeds ADDITIVE float masks ((1-m)*-1e4);
                # this layer's contract is boolean True=attend — passing
                # the additive form through jnp.where would attend exactly
                # the inverted positions with no error
                raise ValueError(
                    "DeepSpeedTransformerLayer takes a boolean/int "
                    "attention_mask (True/1 = attend), not the additive "
                    "float mask; convert with mask = additive_mask >= 0")
            attention_mask = jnp.asarray(attention_mask).astype(bool)
            if attention_mask.ndim == 2:      # HF-style [B, S] key mask
                attention_mask = attention_mask[:, None, None, :]
        if self.config.moe_experts > 0:
            # the single-layer shim has no channel for the router's
            # load-balancing aux loss; dropping it silently would collapse
            # the experts — use build_model(..., moe_experts=...) whose
            # (logits, aux) contract carries it
            raise ValueError(
                "DeepSpeedTransformerLayer does not support MoE configs "
                "(the router aux loss would be silently dropped); build "
                "the full model via models.build_model(moe_experts=...)")
        y, _aux = Block(self.config)(hidden_states, attention_mask, train)
        return y


# reference export name (deepspeed/__init__.py:24-25)
DeepSpeedTransformerConfig = TransformerConfig
