"""Mixture-of-experts: gating, expert-parallel layer, explicit a2a executor."""

from .layer import MoE, ExpertMLP, expert_parallel_apply
from .sharded_moe import (top1_gating, top2_gating, compute_capacity,
                          load_balance_loss)

__all__ = ["MoE", "ExpertMLP", "expert_parallel_apply", "top1_gating",
           "top2_gating", "compute_capacity", "load_balance_loss"]
