"""MoE gating math — top-1 / top-2 with capacity, jitter, load-balance loss.

Capability parity with the reference's ``deepspeed/moe/sharded_moe.py``
(top1gating:177 / top2gating:278: GShard-style dispatch/combine tensors,
capacity + token dropping, load-balancing auxiliary loss, input jitter).
Implemented from the GShard formulation in pure jnp: everything is
einsum/one-hot/cumsum — no sorting networks — so XLA lowers it to MXU-friendly
batched ops and it differentiates cleanly (the combine weights carry the
gradient; the dispatch mask is a stopped-gradient boolean).

Shapes: logits [T, E] -> combine [T, E, C], dispatch [T, E, C] bool,
aux_loss scalar; C = ceil(k * T/E * capacity_factor).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _one_hot(idx, num, dtype=jnp.float32):
    return jax.nn.one_hot(idx, num, dtype=dtype)


def compute_capacity(tokens: int, experts: int, capacity_factor: float,
                     k: int = 1, min_capacity: int = 4) -> int:
    cap = int(math.ceil(k * tokens / experts * capacity_factor))
    return max(cap, min_capacity)


def _positions_in_expert(mask: jnp.ndarray) -> jnp.ndarray:
    """mask [T, E] 0/1 -> position of each token within its expert's queue."""
    return (jnp.cumsum(mask, axis=0) - 1.0) * mask


def load_balance_loss(gates: jnp.ndarray, mask1: jnp.ndarray) -> jnp.ndarray:
    """l_aux = E * sum_e mean_t(gates[:,e]) * mean_t(mask1[:,e])
    (reference: sharded_moe.py top1gating aux_loss; the GShard objective)."""
    E = gates.shape[1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(gates.dtype), axis=0)
    return jnp.sum(me * ce) * E


def top1_gating(logits: jnp.ndarray,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                jitter_eps: float = 0.0,
                rng: Optional[jax.Array] = None,
                capacity: Optional[int] = None
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (aux_loss, combine [T,E,C], dispatch [T,E,C] bool, exp_counts [E])."""
    T, E = logits.shape
    if jitter_eps > 0.0 and rng is not None:
        logits = logits * jax.random.uniform(
            rng, logits.shape, minval=1.0 - jitter_eps, maxval=1.0 + jitter_eps)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    C = capacity if capacity is not None else compute_capacity(
        T, E, capacity_factor, 1, min_capacity)

    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    aux = load_balance_loss(gates, mask1)

    pos1 = _positions_in_expert(mask1)
    keep1 = (pos1 < C) * mask1                         # drop overflow tokens
    gate1 = jnp.sum(gates * keep1, axis=-1)            # [T]

    disp1 = keep1[:, :, None] * _one_hot(pos1.astype(jnp.int32), C)  # [T, E, C]
    dispatch = disp1 > 0.0
    combine = gate1[:, None, None] * jax.lax.stop_gradient(disp1)
    exp_counts = jnp.sum(keep1, axis=0)
    return aux, combine, dispatch, exp_counts


def top2_gating(logits: jnp.ndarray,
                capacity_factor: float = 1.0,
                min_capacity: int = 4,
                rng: Optional[jax.Array] = None,
                capacity: Optional[int] = None
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """GShard top-2: first expert from clean gates, second via Gumbel-max over
    the top-1-masked noisy logits (pass rng=None for noise-free eval); both
    gate values renormalized. (reference: sharded_moe.py:278 top2gating.)"""
    T, E = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    C = capacity if capacity is not None else compute_capacity(
        T, E, capacity_factor, 2, min_capacity)

    # first expert from clean gates; second via Gumbel-max over the masked
    # logits (reference top2gating adds gumbel_rsample noise only for the
    # second pick — sharded_moe.py:278)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    if rng is not None:
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    masked = jnp.where(mask1 > 0, -jnp.inf, logits_w_noise)
    idx2 = jnp.argmax(masked, axis=-1)
    mask2 = _one_hot(idx2, E)

    aux = load_balance_loss(gates, mask1)

    pos1 = _positions_in_expert(mask1)
    # expert queues are shared: second choices queue after first choices
    pos2 = _positions_in_expert(mask2) + jnp.sum(mask1, axis=0, keepdims=True)
    keep1 = (pos1 < C) * mask1
    keep2 = (pos2 < C) * mask2

    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, jnp.finfo(jnp.float32).eps)
    g1, g2 = g1 / denom, g2 / denom

    disp1 = keep1[:, :, None] * _one_hot(pos1.astype(jnp.int32), C)
    disp2 = keep2[:, :, None] * _one_hot(pos2.astype(jnp.int32), C)
    dispatch = (disp1 + disp2) > 0.0
    combine = (g1[:, None, None] * jax.lax.stop_gradient(disp1) +
               g2[:, None, None] * jax.lax.stop_gradient(disp2))
    exp_counts = jnp.sum(keep1 + keep2, axis=0)
    return aux, combine, dispatch, exp_counts
