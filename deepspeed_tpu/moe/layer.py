"""MoE layer — expert-parallel mixture of experts over the 'expert' mesh axis.

Capability parity with the reference's ``deepspeed/moe/layer.py`` (MoE wrapper),
``experts.py`` (local expert stack) and the MOELayer dispatch pipeline
(sharded_moe.py:439: gate -> einsum dispatch -> all_to_all -> expert ->
all_to_all -> einsum combine).

TPU-native execution, two paths:
  * The flax module uses sharding *constraints*: expert weights are stacked
    [E, ...] and constrained to P("expert", ...); the dispatched queue
    [E, C, H] is constrained to P("expert"). XLA's SPMD partitioner inserts
    the token exchange (the reference's `_AllToAll` autograd fn over the
    expert group, sharded_moe.py:89) automatically from the sharding
    mismatch between token-sharded gating and expert-sharded compute.
  * `expert_parallel_apply` is the explicit collective path — a partial-auto
    shard_map whose `lax.all_to_all` pair is exactly GShard's exchange — used
    where hand-placement beats the partitioner and as the comm-correctness
    oracle in tests.

The batch axis is sharded over ("data","expert") — EP is carved out of DP
exactly as the reference carves expert groups from DP ranks
(utils/groups.py:109-262).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharded_moe import compute_capacity, top1_gating, top2_gating

#: canonical intermediate PartitionSpecs of the dispatch pipeline —
#: module-level constants so every reshape lands on the SAME spelling
#: (graftlint TPU008 resolves P(...) literals through these names) and
#: the grouped-layout transitions stay expressible as collectives
#: instead of SPMD replicate-and-reshard fallbacks (ROADMAP item 2a)
TOKEN_AXES = ("data", "expert", "seq")
QUEUE_SPEC = P("expert", ("data", "seq"))
GROUP_SPEC = P(TOKEN_AXES)


def _constrain(x, *spec):
    """Sharding constraint that works under plain jax.jit (resolved against
    the session's global mesh) and inside shard_map contexts (bare spec) —
    see models/transformer._spec_constraint for the rationale."""
    from ..models.transformer import _spec_constraint
    return _spec_constraint(x, P(*spec))


def _warn_ungrouped_fallback(T: int, g: int) -> None:
    """Once-per-(T, g) signal that the dispatch dropped to the ungrouped
    (G=1) layout: token count not divisible by the data*expert*seq mesh
    product reverts to the rematerialization-prone path, and a silent
    fallback makes the resulting perf regression undiagnosable from logs."""
    import jax as _jax
    if _jax.process_index() != 0:
        return
    from ..utils.logging import warning_once
    warning_once(
        f"MoE grouped dispatch disabled: tokens-per-step {T} is not "
        f"divisible by the data*expert*seq mesh product {g}; falling back "
        "to the ungrouped GShard layout, which may trigger involuntary "
        "rematerialization reshards. Pad batch*seq to a multiple of the "
        "mesh product to restore the grouped layout.")


class _Gate(nn.Module):
    """Router projection with the kernel pinned replicated.

    Under ZeRO-3 the [H, E] kernel arrives sharded on its CONTRACTING dim;
    left alone, GSPMD partitions the dot along H and reshards the token
    activations to match — the "involuntary full rematerialization" on the
    moe reshape. Gathering the (tiny) kernel whole instead keeps tokens on
    their batch sharding. Param path stays gate/kernel (nn.Dense parity)."""
    experts: int

    @nn.compact
    def __call__(self, x):
        k = self.param("kernel", nn.initializers.lecun_normal(),
                       (x.shape[-1], self.experts), jnp.float32)
        k = _constrain(k, None, None)
        return x @ k


class ExpertMLP(nn.Module):
    """Default expert: the transformer MLP (fc -> gelu -> proj)."""
    hidden_size: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.mlp_dim, use_bias=self.use_bias, dtype=self.dtype,
                     param_dtype=jnp.float32, name="fc")(x)
        h = nn.gelu(h)
        return nn.Dense(self.hidden_size, use_bias=self.use_bias,
                        dtype=self.dtype, param_dtype=jnp.float32,
                        name="proj")(h)


class GatedExpertMLP(nn.Module):
    """SwiGLU expert (Mixtral-family: HF MixtralBlockSparseTop2MLP w1/w3/w2):
    proj(act(gate(x)) * fc(x)) — the 3-matmul gated MLP as an expert body.
    Param names mirror the dense block's mlp_gate/mlp_fc/mlp_proj roles."""
    hidden_size: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    use_bias: bool = False
    activation: str = "silu"

    @nn.compact
    def __call__(self, x):
        from ..models.transformer import _ACTIVATIONS
        act = _ACTIVATIONS[self.activation]
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=self.use_bias, dtype=self.dtype,
            param_dtype=jnp.float32, name=name)
        g = act(dense(self.mlp_dim, "gate")(x))
        h = g * dense(self.mlp_dim, "fc")(x)
        return dense(self.hidden_size, "proj")(h)


class MoE(nn.Module):
    """Mixture-of-experts block: gate + dispatch + expert-parallel compute.

    Returns (y, aux_loss); callers fold aux_loss into the task loss
    (reference: MoE.forward returns (output, l_aux, exp_counts), layer.py:15).
    """
    hidden_size: int
    num_experts: int
    expert: Optional[Callable[[], nn.Module]] = None
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        B, S, H = x.shape
        E = self.num_experts
        tokens = x.reshape(B * S, H)
        # the merged token dim inherits the batch x seq product sharding —
        # spell it out so SPMD doesn't fall back to replicate-and-reshard
        # (the "involuntary full rematerialization" warning on this reshape)
        tokens = _constrain(tokens, ("data", "expert", "seq"), None)
        T = B * S

        # GShard data layout (reference: sharded_moe.py:89,439 — each rank
        # gates its OWN token slice into a local-capacity queue, then the
        # expert axis exchanges queues with an all-to-all): tokens regroup as
        # [G, T/G, H] with G matching the token dim's mesh sharding, gating
        # runs per group, and the [E, G*Cg, H] queue carries the expert axis
        # on E and the data axes on the queue dim. Without the grouping the
        # partitioner has no valid data-sharded queue layout and falls back
        # to involuntary full rematerialization of the token tensor.
        from ..parallel.mesh import get_global_mesh
        mm = get_global_mesh()
        G = 1
        if mm is not None:
            g = (mm.shape["data"] * mm.shape["expert"] * mm.shape["seq"])
            if T % g == 0:
                G = g
            elif g > 1:
                _warn_ungrouped_fallback(T, g)
        Tg = T // G

        tokens_g = _constrain(tokens.reshape(G, Tg, H),
                              ("data", "expert", "seq"), None, None)
        gate_logits = _Gate(E, name="gate")(
            tokens_g.astype(jnp.float32))                    # [G, Tg, E]
        # top-2 always wants an rng for the Gumbel-max second pick (reference
        # top2gating adds gumbel noise unconditionally in training); fall back
        # to noise-free gating when the caller supplied no "gating" rng stream
        rng = (self.make_rng("gating")
               if train and (self.noisy_gate_policy == "RSample" or self.k == 2)
               and self.has_rng("gating")
               else None)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        Cg = compute_capacity(Tg, E, cf, self.k, self.min_capacity)
        gating = top1_gating if self.k == 1 else top2_gating
        if self.k not in (1, 2):
            raise ValueError(f"k must be 1 or 2, got {self.k}")
        if rng is None:
            gate_one = lambda lg: gating(lg, cf, self.min_capacity,
                                         rng=None, capacity=Cg)
            aux, combine, dispatch, _ = jax.vmap(gate_one)(gate_logits)
        else:
            gate_one = lambda lg, r: gating(lg, cf, self.min_capacity,
                                            rng=r, capacity=Cg)
            aux, combine, dispatch, _ = jax.vmap(gate_one)(
                gate_logits, jax.random.split(rng, G))
        aux = jnp.mean(aux)
        # combine/dispatch: [G, Tg, E, Cg] — group dim stays token-sharded
        dispatch = _constrain(dispatch, ("data", "expert", "seq"),
                              None, None, None)

        # per-group dispatch, then the queue exchange: [G,E,Cg,H] (group-
        # sharded) -> [E, G*Cg, H] (expert-sharded E, data-sharded queue) is
        # the all-to-all of the reference's _AllToAll (sharded_moe.py:89)
        dispatched = jnp.einsum("gtec,gth->gech", dispatch.astype(self.dtype),
                                tokens_g.astype(self.dtype))
        from ..models.transformer import _spec_constraint
        dispatched = _spec_constraint(dispatched, GROUP_SPEC)

        # comm-plan seam: with an active plan routing the expert a2a to a
        # quantized wire format, the exchange pair runs EXPLICITLY (int8
        # payload + blockwise scales through comm.planned); otherwise the
        # canonical constraints below let the SPMD partitioner emit the
        # exact all-to-all from the sharding transition
        xchg_pair = None
        if mm is not None and G > 1 and G == g:
            from ..comm.planned import (moe_exchange_spec,
                                        planned_queue_exchange)
            xchg = moe_exchange_spec(
                mm, dispatched.size * dispatched.dtype.itemsize)
            if xchg is not None:
                algo, bits, blk = xchg
                xchg_pair = planned_queue_exchange(
                    mm.mesh, algo=algo, bits=bits, block=blk)
        if xchg_pair is not None:
            queues = xchg_pair[0](dispatched)            # [E, G*Cg, H]
        else:
            queues = dispatched.transpose(1, 0, 2, 3).reshape(E, G * Cg, H)
            queues = _spec_constraint(queues, QUEUE_SPEC)

        expert_factory = self.expert or (lambda: ExpertMLP(
            self.hidden_size, self.hidden_size * self.mlp_ratio,
            dtype=self.dtype, name="experts"))
        vexpert = nn.vmap(
            lambda mdl, inp: mdl(inp),
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=0, out_axes=0,
            metadata_params={nn.PARTITION_NAME: "expert"},
        )
        expert_out = vexpert(expert_factory(), queues)       # [E, G*Cg, H]
        expert_out = _spec_constraint(expert_out, QUEUE_SPEC)

        # return exchange + per-group combine: the explicit pair inverts
        # the dispatch exchange exactly (row order is self-consistent)
        if xchg_pair is not None:
            out_g = xchg_pair[1](expert_out)             # [G, E, Cg, H]
        else:
            out_g = _spec_constraint(
                expert_out.reshape(E, G, Cg, H).transpose(1, 0, 2, 3),
                GROUP_SPEC)
        y = jnp.einsum("gtec,gech->gth", combine.astype(self.dtype),
                       out_g.astype(self.dtype))
        y = _constrain(y, ("data", "expert", "seq"), None, None)
        return y.reshape(B, S, H), aux.astype(jnp.float32)


def expert_parallel_apply(apply_fn: Callable,
                          expert_params: Any,
                          dispatched: jnp.ndarray,
                          *,
                          mesh,
                          ep: int,
                          expert_axis: str = "expert") -> jnp.ndarray:
    """Explicit GShard exchange: all_to_all -> local experts -> all_to_all.

    apply_fn(params_of_one_expert, x [n, H]) -> [n, H]
    expert_params: stacked [E, ...] leaves, sharded P(expert_axis, ...)
    dispatched: [E, Cq, H] expert queues with the QUEUE dim sharded over the
    expert axis (each ep-rank built its own C = Cq/ep queue slots from its
    token slice — the GShard pre-exchange layout).
    Returns [E, Cq, H] with the same layout.
    """
    E, Cq, H = dispatched.shape
    if E % ep != 0 or Cq % ep != 0:
        raise ValueError(f"experts {E} / queue {Cq} not divisible by ep {ep}")

    def inner(params, disp):
        # disp local: [E, C, H] — this rank's queue slots for ALL experts.
        # exchange: give each rank the full queues of ITS local experts
        x = jax.lax.all_to_all(disp, expert_axis, split_axis=0, concat_axis=1,
                               tiled=True)            # [El, ep*C, H]
        y = jax.vmap(apply_fn)(params, x)             # [El, ep*C, H]
        return jax.lax.all_to_all(y, expert_axis, split_axis=1, concat_axis=0,
                                  tiled=True)         # [E, C, H] local again

    mapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(expert_axis), expert_params),
                  P(None, expert_axis)),
        out_specs=P(None, expert_axis),
        axis_names={expert_axis},
        check_vma=False,
    )
    # partial-auto shard_map requires a jit context (its eager trace path
    # rejects specs over auto axes); calling under jit is also the fast path
    # graftlint: disable=TPU002 (called under the model's outer jit: one construction per outer trace)
    return jax.jit(mapped)(expert_params, dispatched)
