"""THE quantization format — single-sourced blockwise/per-row int8.

Round 17: the repo carried three near-copies of the same symmetric-int8
math — the blockwise wire format in ``runtime/comm/quantized.py``, the
per-(head, position) KV-cache format in ``models/generation``, and the
paged-pool variant documented in ``serving/kv_cache.py``. They are ONE
format family (absmax / 127 symmetric scales over a fixed granularity)
and now live here; every consumer imports these definitions, so the
error model documented in docs/COMM.md ("error <= block_absmax / 127
per element") is a property of one function, not a convention three
files re-implement.

Two granularities:

* **blockwise** (:func:`block_quant` / :func:`block_dequant`): the last
  dim is cut into ``QUANT_BLOCK``-element blocks, one f32 scale each —
  the int8 wire format of the quantized collectives (ZeRO++ qgZ /
  EQuARX style) AND the weight-only decode matmuls
  (``ops/pallas/quant_matmul.py`` stores kernels int8 with the SAME
  per-256-element scales along the contraction dim, dequantized
  in-kernel).
* **per-row** (:func:`kv_quantize`): one f32 scale per trailing row
  (absmax over the last dim) — the KV-cache format shared by the dense
  ``generate()`` cache and the paged serving pool, where a "row" is one
  (layer, head, position/slot) K or V vector and the Pallas paged
  kernel dequantizes it in-kernel (round 17).

:func:`fake_quant_act` is the straight-through activation fake-quant of
the round-17 low-precision training experiment (int8 blockwise or
emulated fp8-e4m3), built on the same blockwise math.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

#: elements per quantization block (one f32 scale each): 256 keeps the
#: scale overhead at 4/256 = 1.6% of the int8 payload while bounding an
#: outlier's blast radius to its own block
QUANT_BLOCK = 256

#: float8_e4m3 dynamic range (finite max) — the fp8 fake-quant scale target
_E4M3_MAX = 448.0


def block_quant(x: jnp.ndarray, bits: int = 8, block: int = QUANT_BLOCK
                ) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Blockwise symmetric quantization of the LAST dim.

    x [..., L] -> (q int8 [..., Lp], scales f32 [..., Lp/block], pad)
    with Lp = L padded up to a block multiple. Zero blocks get scale 1
    (quantize to 0 exactly); q is clipped to the symmetric range.
    Per-element roundtrip error is bounded by block_absmax / (2^(bits-1)
    - 1) — half a quantization step of the block's own scale."""
    qmax = float(2 ** (bits - 1) - 1)
    L = x.shape[-1]
    nb = -(-L // block)
    pad = nb * block - L
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = xf.reshape(x.shape[:-1] + (nb, block))
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(xb / scale), -qmax, qmax).astype(jnp.int8)
    return (q.reshape(x.shape[:-1] + (nb * block,)),
            scale.reshape(x.shape[:-1] + (nb,)), pad)


def block_dequant(q: jnp.ndarray, scales: jnp.ndarray, pad: int
                  ) -> jnp.ndarray:
    """Inverse of :func:`block_quant` (f32 out, padding stripped)."""
    nb = scales.shape[-1]
    block = q.shape[-1] // nb
    xb = q.astype(jnp.float32).reshape(q.shape[:-1] + (nb, block))
    out = (xb * scales[..., None]).reshape(q.shape)
    if pad:
        out = out[..., :q.shape[-1] - pad]
    return out


def kv_quantize(t: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., hd] -> (int8 values, f32 per-row scales [..., 1]).

    One symmetric scale per trailing row (absmax / 127 over the last
    dim; zero rows scale 1) — the KV-cache format: a row is one
    (layer, head, position/slot) K or V vector, in both the dense
    ``generate()`` cache and the paged serving pool."""
    scale = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=-1,
                    keepdims=True) / 127.0
    scale = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return q, scale


def fake_quant_act(x: jnp.ndarray, fmt: str = "int8",
                   block: int = QUANT_BLOCK) -> jnp.ndarray:
    """Straight-through activation fake-quant (round-17 low-precision
    training experiment): the forward value is the ``fmt`` roundtrip of
    ``x`` over the last dim, the gradient passes through untouched.

    * ``"int8"`` — the blockwise format above (error <= block_absmax /
      127 per element).
    * ``"fp8"``  — e4m3-style: one f32 scale per block maps the block's
      absmax onto the e4m3 range, values round through
      ``float8_e4m3fn`` (jax ships ml_dtypes), scale divides back out.
      Emulation of delayed-scaling fp8 compute at bf16 speed — the
      numerics experiment, not the MXU feed.
    """
    if fmt not in ("int8", "fp8"):
        raise ValueError(f"fake_quant_act fmt {fmt!r}: expected int8|fp8")

    @jax.custom_vjp
    def _fq(x):
        if fmt == "int8":
            q, s, pad = block_quant(x, 8, block)
            return block_dequant(q, s, pad).astype(x.dtype)
        L = x.shape[-1]
        nb = -(-L // block)
        pad = nb * block - L
        xf = x.astype(jnp.float32)
        if pad:
            xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        xb = xf.reshape(x.shape[:-1] + (nb, block))
        absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / _E4M3_MAX)
        y = (xb / scale).astype(jnp.float8_e4m3fn).astype(jnp.float32)
        out = (y * scale).reshape(x.shape[:-1] + (nb * block,))
        if pad:
            out = out[..., :L]
        return out.astype(x.dtype)

    _fq.defvjp(lambda x: (_fq(x), None), lambda _, g: (g,))
    return _fq(x)
