"""Elastic agent — restart training on membership change or worker failure.

Capability parity with the reference's ``elasticity/elastic_agent.py:23``
(DSElasticAgent over torch-elastic's LocalElasticAgent: monitor workers,
re-rendezvous and restart on scale-up/down) without the torch rendezvous
store: membership is the hostfile (the thing cluster managers actually
mutate), the agent polls it, and on change — or on worker crash, up to
``max_restarts`` — it terminates the run and relaunches with the new world,
re-deriving the elastic batch config (elasticity.compute_elastic_config's
HCN math) for the new node count. Training resumes from the engine's own
checkpoints (topology-free by construction).
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, Dict, List, Optional

from ..launcher.runner import fetch_hostfile
from ..utils.logging import log_dist, logger

MEMBERSHIP_CHANGED = object()       # monitor sentinel; never equals an rc


class DSElasticAgent:
    def __init__(self,
                 launch_fn: Callable[[List[str]], subprocess.Popen],
                 hostfile: str,
                 max_restarts: int = 100,
                 check_interval: float = 1.0,
                 min_nodes: int = 1):
        """launch_fn(active_hosts) -> Popen for one training run."""
        self.launch_fn = launch_fn
        self.hostfile = hostfile
        self.max_restarts = max_restarts
        self.check_interval = check_interval
        self.min_nodes = min_nodes
        self.restarts = 0
        self.membership_changes = 0

    def _members(self) -> List[str]:
        pool = fetch_hostfile(self.hostfile)
        return list(pool) if pool else ["localhost"]

    def run(self) -> int:
        """Supervise until a run exits 0 (or restarts are exhausted).
        Returns the final exit code (reference: _invoke_run's monitor loop,
        elastic_agent.py:115)."""
        while True:
            members = self._members()
            if len(members) < self.min_nodes:
                logger.warning("elastic agent: %d nodes < min %d; waiting",
                               len(members), self.min_nodes)
                time.sleep(self.check_interval)
                continue
            log_dist(f"elastic agent: launching over {len(members)} nodes "
                     f"(restart {self.restarts})", ranks=[0])
            proc = self.launch_fn(members)
            rc = self._monitor(proc, members)
            if rc == 0:
                return 0
            if rc is MEMBERSHIP_CHANGED:
                self.membership_changes += 1
                continue                      # membership change: relaunch
            self.restarts += 1
            if self.restarts > self.max_restarts:
                logger.error("elastic agent: max_restarts exceeded (rc=%d)",
                             rc)
                return rc

    def _monitor(self, proc: subprocess.Popen, members: List[str]):
        """Poll worker + membership. Returns the worker rc, or the
        MEMBERSHIP_CHANGED sentinel when the hostfile changed (a distinct
        object — a signal-killed worker's negative rc must count as a crash,
        not a rescale)."""
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if self._members() != members:
                log_dist("elastic agent: membership changed — restarting",
                         ranks=[0])
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                return MEMBERSHIP_CHANGED
            time.sleep(self.check_interval)
