"""Elastic agent — restart training on membership change or worker failure.

Capability parity with the reference's ``elasticity/elastic_agent.py:23``
(DSElasticAgent over torch-elastic's LocalElasticAgent: monitor workers,
re-rendezvous and restart on scale-up/down) without the torch rendezvous
store: membership is the hostfile (the thing cluster managers actually
mutate), the agent polls it, and on change — or on worker crash, up to
``max_restarts`` — it terminates the run and relaunches with the new world,
re-deriving the elastic batch config (elasticity.compute_elastic_config's
HCN math) for the new node count. Training resumes from the engine's own
checkpoints (topology-free by construction).

Preemption contract (round-3): a worker that exits with
:data:`PREEMPTION_EXIT_CODE` — what ``engine.install_preemption_handler``
does after its emergency save — is a RESUME, not a crash: the agent
relaunches immediately and does NOT count it against ``max_restarts``
(TPU preemptions at multi-host scale would exhaust any budget).

Stall contract (round-4): a worker the stall watchdog shot
(``runtime.watchdog.STALL_EXIT_CODE``) DOES count against
``max_restarts`` — a wedge is a failure mode, and unbounded relaunching
of a run that wedges deterministically would burn the pod forever. The
agent tracks it separately (``stalls``) so operators can tell "restarted
because wedged" from "restarted because crashed". The run the agent
monitors may be a single worker Popen, a launcher-side ``RunSupervisor``
or a scheduler-side ``BackendSupervisor`` (duck-typed:
poll/wait/terminate/kill), which is how ``dstpu --elastic`` stacks
agent-over-supervisor-over-ranks on every launcher.

Degraded-world contract (round-6): when a COUNTED failure carries host
attribution — the supervisor's ``failed_hosts()`` facade method, plus
heartbeat evidence (``heartbeat_dir``: ranks whose last word is STALLED
or whose record went stale) — the agent strikes those hosts; a host
reaching ``blacklist_after`` strikes is QUARANTINED and the next world
is re-formed from the survivors, so losing a host costs one restart
instead of the run. Quarantine never shrinks the world below
``min_nodes``: when it would, the weakest candidate is paroled instead
(a flaky host beats no pod at all). The surviving world is published to
``active_hostfile`` ("host slots=N" lines, atomic rewrite) for operators
and for scheduler backends that fan out from a hostfile.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, Dict, List, Optional

from ..launcher.runner import fetch_hostfile
from ..utils.logging import log_dist, logger

MEMBERSHIP_CHANGED = object()       # monitor sentinel; never equals an rc

#: Exit code meaning "I was preempted but checkpointed; relaunch me and
#: don't count this against max_restarts". Chosen outside the shell's
#: conventional 126-165 signal range and Python's 0-2. Re-exported from
#: the single-source contract module so the literal lives in one place.
from ..exit_codes import PREEMPTION_EXIT_CODE  # noqa: E402


class DSElasticAgent:
    def __init__(self,
                 launch_fn: Callable[[List[str]], subprocess.Popen],
                 hostfile: str,
                 max_restarts: int = 100,
                 check_interval: float = 1.0,
                 min_nodes: int = 1,
                 confirm_polls: int = 2,
                 teardown_grace: float = 30.0,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_timeout: float = 0.0,
                 blacklist_after: int = 2,
                 active_hostfile: Optional[str] = None):
        """launch_fn(active_hosts) -> Popen for one training run.

        ``confirm_polls``: how many CONSECUTIVE identical polls must agree
        before a hostfile difference counts as a membership change — an
        atomic rewrite of the hostfile mid-poll (truncate+write, or a brief
        unlink during rename) must not look like a rescale.

        ``teardown_grace``: how long a membership-change terminate() may
        take before the agent SIGKILLs — must COVER the run's own
        SIGTERM->grace->SIGKILL window (RunSupervisor's grace_secs, i.e.
        the emergency-checkpoint budget), or the agent's kill races the
        in-flight preemption saves it exists to protect.

        ``heartbeat_dir`` + ``blacklist_after``: degraded-world resume —
        see the module docstring. ``heartbeat_timeout`` (optional) also
        counts ranks whose last record LAGS the channel's freshest record
        by more than that many seconds at failure time as evidence
        against their host (never wall-clock age: by read time the dead
        world has frozen every record)."""
        self.launch_fn = launch_fn
        self.hostfile = hostfile
        self.max_restarts = max_restarts
        self.check_interval = check_interval
        self.min_nodes = min_nodes
        self.confirm_polls = max(1, confirm_polls)
        self.teardown_grace = float(teardown_grace)
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.blacklist_after = max(1, int(blacklist_after))
        self.active_hostfile = active_hostfile
        self.restarts = 0
        self.membership_changes = 0
        self.preemptions = 0
        self.stalls = 0
        self.strikes: Dict[str, int] = {}
        self.blacklisted: set = set()

    def _members(self) -> List[str]:
        pool = self._read_members()
        members = pool if pool else ["localhost"]
        survivors = [h for h in members if h not in self.blacklisted]
        if len(survivors) < self.min_nodes:
            # quarantine must not starve the pod below min_nodes: parole
            # the least-struck hosts back in rather than waiting forever
            parole = sorted((h for h in members if h in self.blacklisted),
                            key=lambda h: self.strikes.get(h, 0))
            while len(survivors) < self.min_nodes and parole:
                host = parole.pop(0)
                self.blacklisted.discard(host)
                self.strikes[host] = 0
                logger.warning(
                    "elastic agent: paroling blacklisted host %s — the "
                    "surviving world would drop below min_nodes=%d",
                    host, self.min_nodes)
                survivors = [h for h in members
                             if h not in self.blacklisted]
        return survivors

    def _read_members(self) -> Optional[List[str]]:
        """Hostfile membership, or None on a transient failure (unreadable
        or empty mid-rewrite) — callers must treat None as 'no evidence',
        never as 'the cluster shrank to nothing'."""
        try:
            pool = fetch_hostfile(self.hostfile)
        except (OSError, ValueError) as e:
            logger.warning("elastic agent: transient hostfile read failure "
                           "(%s); keeping current membership", e)
            return None
        return list(pool) if pool else None

    # ------------------------------------------------------ degraded world

    def _publish_active_world(self, members: List[str]) -> None:
        """Atomically rewrite the active hostfile with the surviving
        world ("host slots=N"; slots looked up from the operator's
        hostfile, defaulting to 1) — the file scheduler backends fan out
        from and operators watch."""
        if not self.active_hostfile:
            return
        try:
            pool = fetch_hostfile(self.hostfile)
        except (OSError, ValueError):
            pool = {}
        lines = "".join(f"{h} slots={pool.get(h, 1)}\n" for h in members)
        try:
            tmp = self.active_hostfile + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(lines)
            os.replace(tmp, self.active_hostfile)
        except OSError as e:
            logger.warning("elastic agent: cannot publish active hostfile "
                           "%s: %s", self.active_hostfile, e)

    def _failure_evidence(self, proc, members: List[str]) -> List[str]:
        """Hosts implicated in a counted failure: the supervisor's own
        attribution first, then the heartbeat channel (ranks whose last
        word is STALLED, or whose record went stale)."""
        from ..runtime import heartbeat as hb
        from ..runtime.straggler import HOST_NAMING_FLAGS
        implicated: List[str] = []
        # the world ranks were ACTUALLY assigned over: launch_fn may narrow
        # the agent's confirmed membership further (--include/--exclude/
        # --num_nodes), so rank->host recovery must index the launched
        # world — both supervisors expose it as rank_hosts — or rank 1's
        # evidence lands on an innocent filtered-out neighbor
        launched = list(getattr(proc, "rank_hosts", None) or members)

        def _rec_host(rec: dict):
            # records SHOULD carry hostfile-vocabulary names (launch.py
            # exports DSTPU_HEARTBEAT_HOST), but a record written by an
            # out-of-band worker self-reports gethostname(); the shared
            # recovery falls back to the rank's position in the launched
            # world so the evidence still lands on a strikable member
            return hb.rec_host(rec, launched, known_hosts=members)

        failed_hosts = getattr(proc, "failed_hosts", None)
        if callable(failed_hosts):
            try:
                implicated.extend(h for h in failed_hosts()
                                  if h and h not in implicated)
            except Exception as e:      # attribution is best-effort
                logger.warning("elastic agent: failed_hosts() raised: %s", e)
        if self.heartbeat_dir:
            for rec in hb.terminal_records(self.heartbeat_dir).values():
                if rec.get("phase") == hb.PHASE_STALLED:
                    host = _rec_host(rec)
                    if host and host not in implicated:
                        implicated.append(host)
            # host-naming flags: SDC (the cross-replica audit aborts
            # EVERY rank with the same rc, but only the implicated
            # rank's record carries the flag) and STRAGGLER (the
            # relative-slowness detector's self-verdict — the rank's
            # rc-117 exit names nobody, the flag names the slow host).
            # Strike that host, not the whole world
            for flag in HOST_NAMING_FLAGS:
                for rec in hb.flagged_ranks(self.heartbeat_dir,
                                            flag=flag).values():
                    host = _rec_host(rec)
                    if host and host not in implicated:
                        implicated.append(host)
            if self.heartbeat_timeout > 0:
                # post-mortem staleness: the world is DOWN by the time the
                # agent reads the channel, so every record is frozen and
                # wall-clock age would implicate the whole (innocent)
                # world — the same frozen-record bug RunSupervisor's
                # at-detection snapshot exists to avoid. A rank that went
                # silent BEFORE the world died instead LAGS the freshest
                # record by more than the timeout; measure against that.
                records = hb.read_heartbeats(self.heartbeat_dir)
                freshest = max((float(r.get("ts", 0.0))
                                for r in records.values()), default=0.0)
                for rec in records.values():
                    if rec.get("phase") in hb.TERMINAL_PHASES:
                        continue
                    lag = freshest - float(rec.get("ts", 0.0))
                    if lag > self.heartbeat_timeout:
                        host = _rec_host(rec)
                        if host and host not in implicated:
                            implicated.append(host)
        return [h for h in implicated if h in members]

    def _record_failures(self, proc, members: List[str]) -> None:
        for host in self._failure_evidence(proc, members):
            self.strikes[host] = self.strikes.get(host, 0) + 1
            if self.strikes[host] >= self.blacklist_after and \
                    host not in self.blacklisted:
                self.blacklisted.add(host)
                logger.error(
                    "elastic agent: quarantining host %s after %d failure "
                    "strike(s) — the next world re-forms from the "
                    "survivors", host, self.strikes[host])

    # -------------------------------------------------------------- monitor

    def run(self) -> int:
        """Supervise until a run exits 0 (or restarts are exhausted).
        Returns the final exit code (reference: _invoke_run's monitor loop,
        elastic_agent.py:115)."""
        while True:
            members = self._members()
            if len(members) < self.min_nodes:
                logger.warning("elastic agent: %d nodes < min %d; waiting",
                               len(members), self.min_nodes)
                time.sleep(self.check_interval)
                continue
            self._publish_active_world(members)
            log_dist(f"elastic agent: launching over {len(members)} nodes "
                     f"(restart {self.restarts}, "
                     f"{len(self.blacklisted)} quarantined)", ranks=[0])
            if self.heartbeat_dir:
                # evidence for the PREVIOUS attempt was read in
                # _record_failures; scope the channel to this attempt so
                # a stale STALLED record can't re-strike a host or turn a
                # clean relaunch's rc into 117
                from ..runtime import heartbeat as hb
                hb.clear_channel(self.heartbeat_dir)
            proc = self.launch_fn(members)
            rc = self._monitor(proc, members)
            if rc == 0:
                return 0
            if rc is MEMBERSHIP_CHANGED:
                self.membership_changes += 1
                continue                      # membership change: relaunch
            if rc == PREEMPTION_EXIT_CODE:
                # graceful preemption: the worker checkpointed on SIGTERM
                # and asked to be resumed — not a failure
                self.preemptions += 1
                log_dist(f"elastic agent: worker preempted (rc={rc}); "
                         f"resuming (preemption {self.preemptions}, not "
                         "counted against max_restarts)", ranks=[0])
                continue
            from ..runtime.watchdog import STALL_EXIT_CODE
            if rc == STALL_EXIT_CODE:
                # the watchdog shot a wedged worker: restart, but COUNT it
                # — a deterministic wedge must not relaunch forever
                self.stalls += 1
                logger.warning("elastic agent: worker stalled (rc=%d, "
                               "stall %d); restarting (counted against "
                               "max_restarts)", rc, self.stalls)
            # counted failure: strike the implicated hosts so a repeat
            # offender is quarantined and the world re-forms without it
            self._record_failures(proc, members)
            self.restarts += 1
            if self.restarts > self.max_restarts:
                logger.error("elastic agent: max_restarts exceeded (rc=%d)",
                             rc)
                return rc

    def _monitor(self, proc: subprocess.Popen, members: List[str]):
        """Poll worker + membership. Returns the worker rc, or the
        MEMBERSHIP_CHANGED sentinel when the hostfile changed (a distinct
        object — a signal-killed worker's negative rc must count as a crash,
        not a rescale).

        A candidate membership change must repeat for ``confirm_polls``
        consecutive polls before it triggers a restart; transient states
        (unreadable/empty hostfile, a half-written rewrite that happens to
        parse) reset the confirmation counter."""
        pending: Optional[List[str]] = None
        agree = 0
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            observed = self._read_members()
            if observed is not None:
                observed = [h for h in observed
                            if h not in self.blacklisted]
            if observed is None or observed == members:
                pending, agree = None, 0
            else:
                if observed == pending:
                    agree += 1
                else:
                    pending, agree = observed, 1
                # checked on EVERY differing poll, including the first —
                # confirm_polls=1 means restart on first confirmed read
                if agree >= self.confirm_polls:
                    log_dist("elastic agent: membership changed — restarting",
                             ranks=[0])
                    proc.terminate()
                    try:
                        # +10s headroom: the run's OWN teardown — the
                        # backend kill-path call (bounded <= 5s), then
                        # grace for emergency checkpoints, then SIGKILL —
                        # must finish before the agent escalates
                        proc.wait(timeout=self.teardown_grace + 10.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    return MEMBERSHIP_CHANGED
            time.sleep(self.check_interval)
