"""Elastic agent — restart training on membership change or worker failure.

Capability parity with the reference's ``elasticity/elastic_agent.py:23``
(DSElasticAgent over torch-elastic's LocalElasticAgent: monitor workers,
re-rendezvous and restart on scale-up/down) without the torch rendezvous
store: membership is the hostfile (the thing cluster managers actually
mutate), the agent polls it, and on change — or on worker crash, up to
``max_restarts`` — it terminates the run and relaunches with the new world,
re-deriving the elastic batch config (elasticity.compute_elastic_config's
HCN math) for the new node count. Training resumes from the engine's own
checkpoints (topology-free by construction).

Preemption contract (round-3): a worker that exits with
:data:`PREEMPTION_EXIT_CODE` — what ``engine.install_preemption_handler``
does after its emergency save — is a RESUME, not a crash: the agent
relaunches immediately and does NOT count it against ``max_restarts``
(TPU preemptions at multi-host scale would exhaust any budget).

Stall contract (round-4): a worker the stall watchdog shot
(``runtime.watchdog.STALL_EXIT_CODE``) DOES count against
``max_restarts`` — a wedge is a failure mode, and unbounded relaunching
of a run that wedges deterministically would burn the pod forever. The
agent tracks it separately (``stalls``) so operators can tell "restarted
because wedged" from "restarted because crashed". The run the agent
monitors may be a single worker Popen or a launcher-side
``RunSupervisor`` (duck-typed: poll/wait/terminate/kill), which is how
``dstpu --elastic`` stacks agent-over-supervisor-over-ranks.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, Dict, List, Optional

from ..launcher.runner import fetch_hostfile
from ..utils.logging import log_dist, logger

MEMBERSHIP_CHANGED = object()       # monitor sentinel; never equals an rc

#: Exit code meaning "I was preempted but checkpointed; relaunch me and
#: don't count this against max_restarts". Chosen outside the shell's
#: conventional 126-165 signal range and Python's 0-2.
PREEMPTION_EXIT_CODE = 114


class DSElasticAgent:
    def __init__(self,
                 launch_fn: Callable[[List[str]], subprocess.Popen],
                 hostfile: str,
                 max_restarts: int = 100,
                 check_interval: float = 1.0,
                 min_nodes: int = 1,
                 confirm_polls: int = 2,
                 teardown_grace: float = 30.0):
        """launch_fn(active_hosts) -> Popen for one training run.

        ``confirm_polls``: how many CONSECUTIVE identical polls must agree
        before a hostfile difference counts as a membership change — an
        atomic rewrite of the hostfile mid-poll (truncate+write, or a brief
        unlink during rename) must not look like a rescale.

        ``teardown_grace``: how long a membership-change terminate() may
        take before the agent SIGKILLs — must COVER the run's own
        SIGTERM->grace->SIGKILL window (RunSupervisor's grace_secs, i.e.
        the emergency-checkpoint budget), or the agent's kill races the
        in-flight preemption saves it exists to protect."""
        self.launch_fn = launch_fn
        self.hostfile = hostfile
        self.max_restarts = max_restarts
        self.check_interval = check_interval
        self.min_nodes = min_nodes
        self.confirm_polls = max(1, confirm_polls)
        self.teardown_grace = float(teardown_grace)
        self.restarts = 0
        self.membership_changes = 0
        self.preemptions = 0
        self.stalls = 0

    def _members(self) -> List[str]:
        pool = self._read_members()
        return pool if pool else ["localhost"]

    def _read_members(self) -> Optional[List[str]]:
        """Hostfile membership, or None on a transient failure (unreadable
        or empty mid-rewrite) — callers must treat None as 'no evidence',
        never as 'the cluster shrank to nothing'."""
        try:
            pool = fetch_hostfile(self.hostfile)
        except (OSError, ValueError) as e:
            logger.warning("elastic agent: transient hostfile read failure "
                           "(%s); keeping current membership", e)
            return None
        return list(pool) if pool else None

    def run(self) -> int:
        """Supervise until a run exits 0 (or restarts are exhausted).
        Returns the final exit code (reference: _invoke_run's monitor loop,
        elastic_agent.py:115)."""
        while True:
            members = self._members()
            if len(members) < self.min_nodes:
                logger.warning("elastic agent: %d nodes < min %d; waiting",
                               len(members), self.min_nodes)
                time.sleep(self.check_interval)
                continue
            log_dist(f"elastic agent: launching over {len(members)} nodes "
                     f"(restart {self.restarts})", ranks=[0])
            proc = self.launch_fn(members)
            rc = self._monitor(proc, members)
            if rc == 0:
                return 0
            if rc is MEMBERSHIP_CHANGED:
                self.membership_changes += 1
                continue                      # membership change: relaunch
            if rc == PREEMPTION_EXIT_CODE:
                # graceful preemption: the worker checkpointed on SIGTERM
                # and asked to be resumed — not a failure
                self.preemptions += 1
                log_dist(f"elastic agent: worker preempted (rc={rc}); "
                         f"resuming (preemption {self.preemptions}, not "
                         "counted against max_restarts)", ranks=[0])
                continue
            from ..runtime.watchdog import STALL_EXIT_CODE
            if rc == STALL_EXIT_CODE:
                # the watchdog shot a wedged worker: restart, but COUNT it
                # — a deterministic wedge must not relaunch forever
                self.stalls += 1
                logger.warning("elastic agent: worker stalled (rc=%d, "
                               "stall %d); restarting (counted against "
                               "max_restarts)", rc, self.stalls)
            self.restarts += 1
            if self.restarts > self.max_restarts:
                logger.error("elastic agent: max_restarts exceeded (rc=%d)",
                             rc)
                return rc

    def _monitor(self, proc: subprocess.Popen, members: List[str]):
        """Poll worker + membership. Returns the worker rc, or the
        MEMBERSHIP_CHANGED sentinel when the hostfile changed (a distinct
        object — a signal-killed worker's negative rc must count as a crash,
        not a rescale).

        A candidate membership change must repeat for ``confirm_polls``
        consecutive polls before it triggers a restart; transient states
        (unreadable/empty hostfile, a half-written rewrite that happens to
        parse) reset the confirmation counter."""
        pending: Optional[List[str]] = None
        agree = 0
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            observed = self._read_members()
            if observed is None or observed == members:
                pending, agree = None, 0
            else:
                if observed == pending:
                    agree += 1
                else:
                    pending, agree = observed, 1
                # checked on EVERY differing poll, including the first —
                # confirm_polls=1 means restart on first confirmed read
                if agree >= self.confirm_polls:
                    log_dist("elastic agent: membership changed — restarting",
                             ranks=[0])
                    proc.terminate()
                    try:
                        # +5s headroom: the run's OWN teardown (grace for
                        # emergency checkpoints, then SIGKILL) must finish
                        # before the agent escalates
                        proc.wait(timeout=self.teardown_grace + 5.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
                    return MEMBERSHIP_CHANGED
            time.sleep(self.check_interval)
