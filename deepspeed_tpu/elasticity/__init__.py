"""Elastic training: world-size-compatible batch configuration math."""

from .elastic_agent import PREEMPTION_EXIT_CODE, DSElasticAgent
from .elasticity import (HCN_LIST, ElasticityError, compute_elastic_config,
                         get_best_candidates, get_valid_gpus)

__all__ = ["HCN_LIST", "ElasticityError", "compute_elastic_config",
           "get_best_candidates", "get_valid_gpus", "DSElasticAgent",
           "PREEMPTION_EXIT_CODE"]
