"""Elastic training: world-size-compatible batch configuration math."""

from .elasticity import (HCN_LIST, ElasticityError, compute_elastic_config,
                         get_best_candidates, get_valid_gpus)

__all__ = ["HCN_LIST", "ElasticityError", "compute_elastic_config",
           "get_best_candidates", "get_valid_gpus"]
