"""Elastic training batch math — world-size-compatible batch configurations.

Capability parity with the reference's ``elasticity/elasticity.py`` (v0.1/0.2):
from a target max batch size + admissible micro-batch sizes + a node range,
pick a global batch size that stays valid (divisible into micro*gas*world)
across as many world sizes as possible, so a job restarted at a different
scale keeps a compatible batch. The candidate enumeration follows the same
highly-composite-number idea (HCN_LIST, elasticity.py:19-58): HCNs maximize
divisor count and therefore the set of compatible world sizes.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

# highly composite numbers — maximal divisor counts (reference: HCN_LIST)
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720,
            45360, 50400, 55440, 83160, 110880, 166320, 221760, 277200]

LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


def get_valid_gpus(batch_size: int, micro_batches: Sequence[int],
                   min_gpus: int, max_gpus: int) -> List[int]:
    """World sizes at which batch_size = mb * gas * gpus for some mb, gas>=1."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        per_mb = batch_size // mb
        for g in range(min_gpus, max_gpus + 1):
            if per_mb % g == 0:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(max_acceptable_batch_size: int,
                        micro_batches: Sequence[int],
                        min_gpus: int, max_gpus: int,
                        prefer_larger: bool = True
                        ) -> Tuple[int, List[int]]:
    """Candidate batches mb*HCN <= max; pick the one valid at the most world
    sizes (ties broken toward larger/smaller batch per prefer_larger)."""
    best_batch, best_valid = None, []
    for mb in sorted(set(micro_batches)):
        for hcn in HCN_LIST:
            b = mb * hcn
            if b > max_acceptable_batch_size:
                break
            valid = get_valid_gpus(b, micro_batches, min_gpus, max_gpus)
            better = (len(valid) > len(best_valid) or
                      (len(valid) == len(best_valid) and best_batch is not None
                       and (b > best_batch if prefer_larger else b < best_batch)))
            if best_batch is None or better:
                best_batch, best_valid = b, valid
    if best_batch is None or not best_valid:
        raise ElasticityError(
            f"no valid elastic batch <= {max_acceptable_batch_size} for "
            f"micro_batches {list(micro_batches)} and gpu range "
            f"[{min_gpus}, {max_gpus}]")
    return best_batch, best_valid


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0) -> Tuple[int, List[int], int]:
    """(final_batch_size, valid_gpus, micro_batch_for_world_size).

    reference signature: elasticity.py compute_elastic_config; raises if the
    current world size is not among the valid ones.
    """
    e = ds_config.get("elasticity", {})
    if not e.get("enabled", False):
        raise ElasticityError("elasticity section missing or disabled")
    version = float(e.get("version", 0.1))
    if version > LATEST_ELASTICITY_VERSION:
        raise ElasticityError(f"unsupported elasticity version {version}")
    micro_batches = e.get("micro_batch_sizes", [2, 4, 6])
    max_batch = int(e.get("max_train_batch_size", 2000))
    min_gpus = int(e.get("min_gpus", 1))
    max_gpus = int(e.get("max_gpus", 10000))
    prefer_larger = bool(e.get("prefer_larger_batch", True))

    final_batch, valid_gpus = get_best_candidates(
        max_batch, micro_batches, min_gpus, max_gpus, prefer_larger)

    micro = 0
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityError(
                f"world size {world_size} not in valid elastic gpu counts "
                f"{valid_gpus} for batch {final_batch}")
        # largest admissible micro batch that divides the per-gpu share
        per_gpu = final_batch // world_size
        fitting = [mb for mb in micro_batches if per_gpu % mb == 0]
        if not fitting:
            raise ElasticityError(
                f"no micro batch in {micro_batches} divides {per_gpu}")
        micro = max(fitting)
    return final_batch, valid_gpus, micro
