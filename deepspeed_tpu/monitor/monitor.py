"""Monitoring: fan out scalar events to TensorBoard / WandB / CSV.

Capability parity with the reference's ``deepspeed/monitor/*`` (MonitorMaster
dispatching to TensorboardMonitor / WandbMonitor / csvMonitor on rank 0).
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import jax

from ..utils.logging import logger

# event = (tag, value, step)
Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = False

    def write_events(self, events: List[Event]):
        raise NotImplementedError


class CSVMonitor(Monitor):
    """reference: monitor/csv_monitor.py — one csv file per tag."""

    def __init__(self, config):
        self.enabled = config.enabled and jax.process_index() == 0
        self._files = {}
        if self.enabled:
            self.out_dir = os.path.join(config.output_path or "csv_monitor_output",
                                        config.job_name)
            os.makedirs(self.out_dir, exist_ok=True)

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            fname = os.path.join(self.out_dir, tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, float(value)])


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        self.enabled = False
        self.writer = None
        if config.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self.writer = SummaryWriter(
                    log_dir=os.path.join(config.output_path or "tb_logs", config.job_name))
                self.enabled = True
            except Exception as e:  # tensorboard not installed
                logger.warning(f"tensorboard unavailable, disabling: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.writer.add_scalar(tag, float(value), step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        self.enabled = False
        if config.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=config.project, group=config.group, entity=config.team)
                self._wandb = wandb
                self.enabled = True
            except Exception as e:
                logger.warning(f"wandb unavailable, disabling: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self._wandb.log({tag: float(value)}, step=step)


class MonitorMaster(Monitor):
    """reference: monitor/monitor.py:24 — dispatches to all enabled backends."""

    def __init__(self, ds_config):
        self.monitors: List[Monitor] = [
            CSVMonitor(ds_config.csv_monitor),
            TensorBoardMonitor(ds_config.tensorboard),
            WandbMonitor(ds_config.wandb),
        ]
        self.enabled = any(m.enabled for m in self.monitors)

    def write_events(self, events: List[Event]):
        for m in self.monitors:
            m.write_events(events)
