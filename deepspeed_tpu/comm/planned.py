"""Plan-routed collective entry points — the comm-facade face of
``comm_plan``.

Wiring sites call ONE function here instead of picking a wire format
themselves: the engine's ZeRO-2 grad sync calls
:func:`planned_grad_sync` with the algorithm its init-time resolution
chose, the ZeRO-3 param fetch builds its per-leaf chunked gathers via
:func:`planned_param_gather`, and the MoE dispatch asks
:func:`moe_exchange_spec` at trace time
(reading the engine-installed plan context) whether — and how — the
queue exchange should leave the implicit-SPMD path. Execution lives in
``runtime/comm/quantized.py``; policy lives in ``comm_plan/``; this
module is the seam between them, mirroring how ``comm.comm`` fronts the
raw ``jax.lax`` collectives.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..comm_plan.runtime import active_context, resolve_algo
from ..runtime.comm.overlap import (make_overlap_gather, overlap_grad_sync,
                                    OVERLAP_ALGOS)
from ..runtime.comm.quantized import grad_sync, make_queue_exchange


def planned_grad_sync(x, *, mesh, axis="data", algo: str = "int8",
                      bits: int = 8, block: int = 256, mean: bool = True,
                      chunks: int = 4):
    """The ZeRO-2 grad-sync entry point: stacked per-rank grads in,
    reduced (replicated) grads out, wire format (and schedule — the
    ``overlap`` family chunks the sync so no tail-end whole-tensor
    collective remains) per ``algo``."""
    if algo in OVERLAP_ALGOS:
        return overlap_grad_sync(x, mesh=mesh, axis=axis, chunks=chunks,
                                 algo=algo, bits=bits, block=block,
                                 mean=mean)
    return grad_sync(x, mesh=mesh, axis=axis, algo=algo, bits=bits,
                     block=block, mean=mean)


def planned_param_gather(mesh, axis, dim: int, *, algo: str,
                         chunks: int = 4, bits: int = 8, block: int = 256):
    """Per-leaf ZeRO-3 param-fetch executor for the ``overlap`` family:
    the chunked explicit all-gather (forward) whose autodiff transpose
    is the chunked grad reduce-scatter (backward) — see
    ``runtime.comm.overlap.make_overlap_gather``."""
    return make_overlap_gather(mesh, axis, dim, chunks=chunks, algo=algo,
                               bits=bits, block=block)


def moe_exchange_spec(mesh, nbytes: int
                      ) -> Optional[Tuple[str, int, int]]:
    """Consulted by ``moe.layer.MoE`` at trace time: returns
    ``(algo, bits, block)`` when the active plan routes the expert
    all-to-all through the EXPLICIT exchange, or None to stay on the
    implicit constraint-driven path (no context installed, a
    single-member expert axis, or an exact verdict)."""
    ctx = active_context()
    if ctx is None:
        return None
    ep = mesh.shape.get("expert", 1)
    if ep <= 1:
        return None
    algo = resolve_algo(ctx, "moe_all_to_all", "expert", nbytes,
                        axis_size=ep)
    if algo == "exact":
        return None
    return algo, ctx.bits, ctx.block


def planned_queue_exchange(mesh, *, algo: str, bits: int = 8,
                           block: int = 256):
    """(dispatch, combine) pair for the grouped MoE layout — see
    ``runtime.comm.quantized.make_queue_exchange``."""
    return make_queue_exchange(mesh, algo=algo, bits=bits, block=block)
