from .comm import (
    ReduceOp,
    all_reduce,
    all_gather,
    reduce_scatter,
    all_to_all,
    broadcast,
    ppermute,
    send_recv_next,
    send_recv_prev,
    axis_rank,
    axis_size,
    barrier,
    init_distributed,
    is_initialized,
    get_rank,
    get_world_size,
    get_local_rank,
    get_device_count,
    log_summary,
    get_comms_logger,
    configure,
)
from .logging import CommsLogger
from .planned import (
    moe_exchange_spec,
    planned_grad_sync,
    planned_queue_exchange,
)
