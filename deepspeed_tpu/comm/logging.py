"""Per-op communication accounting.

Capability parity with the reference's ``deepspeed/utils/comms_logging.py``
(CommsLogger: per-op records + log_summary table).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} PB"


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug
        # op name -> list of (nbytes, seconds)
        self.comms_dict: Dict[str, List] = defaultdict(list)

    def configure(self, enabled: bool = False, verbose: bool = False,
                  prof_all: bool = True, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.debug = debug

    def append(self, op_name: str, nbytes: int, seconds: float):
        self.comms_dict[op_name].append((nbytes, seconds))
        if self.verbose:
            from ..utils.logging import logger
            logger.info(f"comm op: {op_name} | size: {_fmt_bytes(nbytes)}")

    def reset(self):
        self.comms_dict.clear()

    def log_summary(self) -> str:
        lines = [f"{'Op':<20}{'Count':>8}{'Total Size':>14}{'Total Trace Time':>18}"]
        for op, recs in sorted(self.comms_dict.items()):
            total_bytes = sum(r[0] for r in recs)
            total_time = sum(r[1] for r in recs)
            lines.append(f"{op:<20}{len(recs):>8}{_fmt_bytes(total_bytes):>14}"
                         f"{total_time * 1e3:>15.2f} ms")
        out = "\n".join(lines)
        from ..utils.logging import logger
        logger.info("\n" + out)
        return out
