"""deepspeed.comm-equivalent facade over XLA collectives.

Capability parity with the reference's ``deepspeed/comm/comm.py`` (module-level
collective API + ``timed_op`` logging + ``init_distributed`` bootstrap) and
``comm/backend.py`` (pluggable Backend). On TPU the transport is XLA over
ICI/DCN: *inside* jit/shard_map, collectives are `jax.lax` ops over named mesh
axes; process bootstrap is ``jax.distributed.initialize``. The facade keeps the
reference's op-level accounting surface (CommsLogger / log_summary), recording
traffic at trace time (per-op wall timing inside a compiled program is not
meaningful under XLA — the whole point is fusion/overlap).

Every collective defined here is cataloged by graftlint's collective model
(analysis/collectives.py FACADE_COLLECTIVES), which drives the
interprocedural safety rules TPU011–TPU013 (rank-divergent reachability,
axis validity, ordering) — add any new collective wrapper to that catalog
so callers get the same static guarantees through the facade as through
``jax.lax`` directly.
"""

from __future__ import annotations

import functools
import os
import time
from enum import Enum
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import logger
from .logging import CommsLogger

AxisName = Union[str, Sequence[str]]


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


_comms_logger = CommsLogger()
_initialized = False


def configure(comms_config=None) -> None:
    """Wire the comms logger from a DeepSpeedConfig.comms_logger section."""
    if comms_config is not None:
        _comms_logger.configure(enabled=comms_config.enabled, verbose=comms_config.verbose,
                                prof_all=comms_config.prof_all, debug=comms_config.debug)


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     **kwargs) -> None:
    """Multi-host bootstrap. reference: comm/comm.py:599-662.

    Single-process (or already-initialized) is a no-op. Multi-host TPU pods are
    detected from the standard coordinator env vars or explicit arguments and
    routed to ``jax.distributed.initialize`` (the TPU-native rendezvous,
    replacing torch.distributed.init_process_group + NCCL).
    """
    global _initialized
    if _initialized:
        return
    coord = init_method or os.environ.get("DSTPU_COORDINATOR_ADDRESS")
    n_proc = world_size if world_size > 0 else int(os.environ.get("DSTPU_NUM_PROCESSES", "0") or 0)
    pid = rank if rank >= 0 else int(os.environ.get("DSTPU_PROCESS_ID", "-1"))
    if coord and n_proc > 1:
        # a dead/unreachable coordinator blocks initialize forever with no
        # diagnostics; under DSTPU_INIT_TIMEOUT the worker dumps stacks and
        # exits the stall rc instead (launcher supervision tears down fast)
        from ..runtime.watchdog import init_deadline
        init_timeout = float(kwargs.pop("initialization_timeout", 0) or
                             os.environ.get("DSTPU_INIT_TIMEOUT", "0") or 0)
        with init_deadline(init_timeout):
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=n_proc,
                                       process_id=pid)
        logger.info(f"jax.distributed initialized: process {pid}/{n_proc} @ {coord}")
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("DSTPU_LOCAL_RANK", "0"))


def get_device_count() -> int:
    return jax.device_count()


def barrier(name: str = "dstpu_barrier") -> None:
    """Cross-host barrier. reference: comm/comm.py barrier()."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def _nbytes(x) -> int:
    return int(x.size) * x.dtype.itemsize if hasattr(x, "size") else 0


def timed_op(fn):
    """Record per-op traffic (count/bytes) at trace time. reference: comm.py:112-153."""

    @functools.wraps(fn)
    def wrapper(tensor, *args, **kwargs):
        t0 = time.time()
        out = fn(tensor, *args, **kwargs)
        if _comms_logger.enabled:
            _comms_logger.append(fn.__name__, _nbytes(tensor), time.time() - t0)
        return out

    return wrapper


# ---------------------------------------------------------------------------
# In-program collectives over named mesh axes (call inside jit / shard_map).
# Each maps a reference API (comm/torch.py) onto the XLA primitive that rides
# ICI/DCN. `axis` is a mesh axis name or tuple of names.
# ---------------------------------------------------------------------------

@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, axis: AxisName = "data"):
    """reference: torch.distributed.all_reduce → lax.psum/pmax/pmin/pmean."""
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axis)
    if op == ReduceOp.PRODUCT:
        # sign-safe product: magnitude via psum of log|x|, sign via parity of
        # negative counts (a bare exp(psum(log x)) would NaN on x<=0)
        mag = jnp.exp(lax.psum(jnp.log(jnp.abs(tensor)), axis))
        neg = lax.psum((tensor < 0).astype(jnp.int32), axis)
        sign = 1.0 - 2.0 * (neg % 2).astype(tensor.dtype)
        return jnp.where(lax.pmin(jnp.abs(tensor), axis) == 0, 0.0, sign * mag)
    raise ValueError(f"unsupported op {op}")


@timed_op
def all_gather(tensor, axis: AxisName = "data", tiled: bool = True, gather_dim: int = 0):
    """reference: all_gather_base → lax.all_gather (tiled = concatenate along dim)."""
    return lax.all_gather(tensor, axis, axis=gather_dim, tiled=tiled)


@timed_op
def reduce_scatter(tensor, axis: AxisName = "data", scatter_dim: int = 0,
                   op: ReduceOp = ReduceOp.SUM):
    """reference: reduce_scatter_base → lax.psum_scatter."""
    out = lax.psum_scatter(tensor, axis, scatter_dimension=scatter_dim, tiled=True)
    if op == ReduceOp.AVG:
        out = out / lax.axis_size(axis)
    return out


@timed_op
def all_to_all(tensor, axis: AxisName = "expert", split_dim: int = 0, concat_dim: int = 0):
    """reference: all_to_all_single → lax.all_to_all (MoE dispatch/combine)."""
    return lax.all_to_all(tensor, axis, split_axis=split_dim, concat_axis=concat_dim,
                          tiled=True)


@timed_op
def broadcast(tensor, src: int = 0, axis: AxisName = "data"):
    """Broadcast src's copy along ``axis``: mask + psum (XLA lowers to a bcast)."""
    idx = lax.axis_index(axis)
    mask = (idx == src).astype(tensor.dtype)
    return lax.psum(tensor * mask, axis)


@timed_op
def ppermute(tensor, perm, axis: AxisName = "pipe"):
    """Neighbor exchange (pipeline P2P). reference: pipe/p2p.py send/recv pairs."""
    return lax.ppermute(tensor, axis, perm=perm)


def send_recv_next(tensor, axis: AxisName = "pipe"):
    """Shift +1 along axis ring: stage i's value arrives at stage i+1."""
    n = lax.axis_size(axis)
    return lax.ppermute(tensor, axis, perm=[(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(tensor, axis: AxisName = "pipe"):
    n = lax.axis_size(axis)
    return lax.ppermute(tensor, axis, perm=[(i, (i - 1) % n) for i in range(n)])


def axis_rank(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return lax.axis_size(axis)


# ---------------------------------------------------------------------------
# Logging rollups
# ---------------------------------------------------------------------------

def log_summary() -> str:
    """reference: comm/comm.py:483 log_summary()."""
    return _comms_logger.log_summary()


def get_comms_logger() -> CommsLogger:
    return _comms_logger
