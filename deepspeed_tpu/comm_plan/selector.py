"""Plan selection: sweep records in, CommPlan out.

Records are the ``comm_bench: {json}`` rows ``benchmarks/communication.py``
emits — one dict per (op, algo, axis, size) with a measured
``latency_us``. The selector groups them by (kind, axis, bucket) and
picks the fastest algorithm per cell; ties break toward the SAFER
algorithm (lower index in :data:`plan.ALGOS`, i.e. ``exact`` first), and
record order never matters — same sweep, same plan, byte for byte.

Where no sweep covers a query, :func:`heuristic_algo` applies the safe
size-threshold policy: exact below the threshold (latency-bound regime —
quantize/dequant overhead and scale traffic buy nothing), int8 above it
(bandwidth-bound — the 4x payload cut is the win ZeRO++/EQuARX measure),
and always exact on a single-member axis (nothing to exchange). The
``overlap`` family is deliberately NEVER a heuristic verdict: whether a
hand-pipelined chunk schedule beats the scheduler is a property of the
host's wire, so overlap is only ever selected from recorded sweep rows
(whose latency_us is the overlap cell's EXPOSED comm time) or forced by
an override — never hard-coded.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

from .plan import ALGOS, CommPlan, PlanEntry, bucket_of

BENCH_PREFIX = "comm_bench:"

#: heuristic regime boundary (bytes): messages at or above quantize
DEFAULT_SIZE_THRESHOLD = 4 * 2 ** 20


def parse_bench_lines(text: str) -> List[Dict]:
    """Extract the machine-readable sweep rows from benchmark stdout.
    Malformed lines are skipped (a truncated run keeps its good rows)."""
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith(BENCH_PREFIX):
            continue
        try:
            row = json.loads(line[len(BENCH_PREFIX):])
        except ValueError:
            continue
        if isinstance(row, dict) and "op" in row:
            rows.append(row)
    return rows


def _row_bytes(row: Dict) -> Optional[int]:
    if "size_bytes" in row:
        return int(row["size_bytes"])
    if "size_mb" in row:
        return int(float(row["size_mb"]) * 2 ** 20)
    return None


def select_plan(records: Iterable[Dict], meta: Optional[Dict] = None
                ) -> CommPlan:
    """argmin-latency per (kind, axis, bucket); deterministic under
    record shuffling (ties break by latency, then ALGOS order)."""
    cells: Dict[tuple, List[Dict]] = {}
    for row in records:
        nbytes = _row_bytes(row)
        algo = row.get("algo", "exact")
        if nbytes is None or "latency_us" not in row or algo not in ALGOS:
            continue
        key = (str(row["op"]), str(row.get("axis", "all")),
               bucket_of(nbytes))
        cells.setdefault(key, []).append(row)
    plan = CommPlan(meta=dict(meta or {}))
    for (kind, axis, bucket), rows in cells.items():
        best = min(rows, key=lambda r: (float(r["latency_us"]),
                                        ALGOS.index(r.get("algo",
                                                          "exact"))))
        plan.add(PlanEntry(kind=kind, axis=axis, bucket=bucket,
                           algo=best.get("algo", "exact"),
                           est_us=float(best["latency_us"]),
                           source="sweep"))
    return plan


def heuristic_algo(kind: str, nbytes: int, axis_size: int,
                   size_threshold: int = DEFAULT_SIZE_THRESHOLD) -> str:
    """The no-sweep fallback policy. Conservative by construction: only
    the two kinds with a quantized implementation ever leave exact."""
    if axis_size <= 1:
        return "exact"
    if kind in ("reduce_scatter", "all_to_all", "all_reduce") and \
            nbytes >= size_threshold:
        return "int8"
    return "exact"
