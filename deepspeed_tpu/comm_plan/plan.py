"""CommPlan — the JSON-serializable per-collective algorithm table.

A plan maps (collective kind, mesh axis, message-size bucket) to one of
the algorithm names in :data:`ALGOS`. Kinds are the WIRE ops the
benchmark sweeps measure (``all_reduce``/``all_gather``/
``reduce_scatter``/``all_to_all``); the engine's wiring sites consult
them through site aliases (``grad_reduce_scatter`` -> ``reduce_scatter``,
``moe_all_to_all`` -> ``all_to_all``, ``param_all_gather`` ->
``all_gather``) so a single sweep steers every training seam and any
future caller of the same wire op.

Buckets are ceil(log2(message bytes)) — one decision per octave of
message size, matching how collective latency curves actually bend (a
flat latency floor below ~1 MB, bandwidth-bound above). An axis of
``"all"`` (the benchmark's flat mesh) acts as the wildcard row for axes
without their own sweep.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

#: every algorithm name a plan may mention, ordered safest-first (the
#: selector's tie-break): exact moves exact whole tensors, overlap moves
#: exact CHUNKS (same math, hand-pipelined wire schedule — T3-style
#: chunked allgather->matmul / chunked grad reduce-scatter), int8 and
#: overlap_int8 put the blockwise-quantized format on the wire
ALGOS = ("exact", "overlap", "int8", "overlap_int8", "hierarchical",
         "onebit")

#: algorithms whose wire format is LOSSY — the accuracy guard's exact
#: latch applies to these only (overlap moves exact values; forcing it
#: back to a whole-tensor schedule would change nothing numerically)
QUANTIZED_ALGOS = frozenset(("int8", "overlap_int8", "hierarchical",
                             "onebit"))

#: algorithms each engine wiring SITE can actually execute. The plan/
#: selector may know more (the benchmark measures onebit/hierarchical
#: allreduce too); a site falls back to its own ladder when the chosen
#: algo is not executable at that seam.
SITE_ALGOS = {
    "grad_reduce_scatter": ("exact", "int8", "overlap", "overlap_int8"),
    "moe_all_to_all": ("exact", "int8"),
    "param_all_gather": ("exact", "overlap", "overlap_int8"),
}

#: site alias -> wire kind the sweeps record
SITE_KIND = {
    "grad_reduce_scatter": "reduce_scatter",
    "moe_all_to_all": "all_to_all",
    "param_all_gather": "all_gather",
}

PLAN_VERSION = 1


def bucket_of(nbytes: int) -> int:
    """Message-size bucket: ceil(log2(bytes)), floored at 2^10 (sub-KiB
    messages share one latency-floor bucket)."""
    return max(10, math.ceil(math.log2(max(int(nbytes), 1))))


@dataclass
class PlanEntry:
    kind: str
    axis: str
    bucket: int
    algo: str
    est_us: Optional[float] = None      # selector's winning latency
    source: str = "sweep"               # sweep | heuristic | override

    def key(self) -> Tuple[str, str, int]:
        return (self.kind, self.axis, self.bucket)


@dataclass
class CommPlan:
    """Decision table + provenance. ``choose`` returns None when no entry
    covers the query — callers fall through to the heuristic ladder."""

    entries: Dict[Tuple[str, str, int], PlanEntry] = field(
        default_factory=dict)
    meta: Dict = field(default_factory=dict)
    version: int = PLAN_VERSION

    def add(self, entry: PlanEntry) -> None:
        self.entries[entry.key()] = entry

    def choose(self, kind: str, axis: str, nbytes: int) -> Optional[str]:
        """Exact (kind, axis, bucket) row, else the (kind, 'all', bucket)
        wildcard. Unknown bucket -> None (heuristic fallback)."""
        b = bucket_of(nbytes)
        e = self.entries.get((kind, axis, b)) or \
            self.entries.get((kind, "all", b))
        return e.algo if e is not None else None

    def entry_for(self, kind: str, axis: str,
                  nbytes: int) -> Optional[PlanEntry]:
        b = bucket_of(nbytes)
        return self.entries.get((kind, axis, b)) or \
            self.entries.get((kind, "all", b))

    # -- serialization -------------------------------------------------------

    def to_json(self) -> str:
        rows = [asdict(self.entries[k])
                for k in sorted(self.entries)]
        return json.dumps({"version": self.version, "meta": self.meta,
                           "entries": rows}, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "CommPlan":
        doc = json.loads(text)
        ver = doc.get("version", PLAN_VERSION)
        if ver > PLAN_VERSION:
            raise ValueError(
                f"comm plan version {ver} is newer than this build "
                f"understands ({PLAN_VERSION})")
        plan = cls(meta=dict(doc.get("meta") or {}), version=ver)
        for row in doc.get("entries", ()):
            algo = row.get("algo")
            if algo not in ALGOS:
                raise ValueError(f"comm plan entry has unknown algo "
                                 f"{algo!r} (known: {ALGOS})")
            plan.add(PlanEntry(kind=row["kind"], axis=row["axis"],
                               bucket=int(row["bucket"]), algo=algo,
                               est_us=row.get("est_us"),
                               source=row.get("source", "sweep")))
        return plan

    def save(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "CommPlan":
        with open(path) as f:
            return cls.from_json(f.read())

    def describe(self) -> str:
        if not self.entries:
            return "comm plan: empty (heuristics apply everywhere)"
        lines = [f"{'kind':<16} {'axis':<8} {'bucket':<8} {'~size':<10} "
                 f"{'algo':<12} {'est_us':<10} source"]
        for key in sorted(self.entries):
            e = self.entries[key]
            size = 2 ** e.bucket
            human = (f"{size // 2**20}MiB" if size >= 2 ** 20
                     else f"{size // 2**10}KiB")
            lines.append(
                f"{e.kind:<16} {e.axis:<8} {e.bucket:<8} {'<=' + human:<10} "
                f"{e.algo:<12} "
                f"{'' if e.est_us is None else round(e.est_us, 1):<10} "
                f"{e.source}")
        return "\n".join(lines)
