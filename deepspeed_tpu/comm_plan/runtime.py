"""Active-plan context + the algorithm resolution ladder.

The engine installs a :class:`PlanContext` around its traced programs
(``use_context`` wraps ``apply_fn``), so model-internal seams — the MoE
dispatch — read the plan at TRACE time without any config threading
through module pytrees. The context is a thread-local stack: two engines
in one process (the test suite's exact-vs-quantized twins) each see only
their own plan, and an engine with comm_plan disabled sees none.

Resolution ladder for a site query (:func:`resolve_algo`):

1. explicit per-kind override from the ``comm_plan`` config section
   (site alias first, then the wire kind) — unsupported algos RAISE, a
   forced choice must not silently degrade;
2. the loaded plan's (kind, axis, bucket) entry — entries naming an algo
   the site cannot execute fall through (the plan also steers benchmark
   kinds the engine has no seam for);
3. the size-threshold heuristic.

The :class:`AccuracyGuard` is the engine-side safety valve: when the
observed global grad norm drops below ``guard_min_grad_norm``, the next
steps run the EXACT program — near convergence (or during a warmup with
tiny grads) the blockwise-int8 quantization error is no longer small
relative to the signal. The guard only ever forces exact; it never
promotes a collective to a quantized algorithm.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Optional

from .plan import CommPlan, SITE_ALGOS, SITE_KIND
from .selector import heuristic_algo

_tls = threading.local()


@dataclass
class PlanContext:
    """Everything a wiring site needs to pick an algorithm."""
    plan: Optional[CommPlan] = None
    overrides: dict = field(default_factory=dict)
    bits: int = 8
    block: int = 256
    size_threshold: int = 4 * 2 ** 20
    overlap_chunks: int = 4            # pieces per overlap-family collective
    resolved: dict = field(default_factory=dict)   # site -> algo (audit)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


@contextlib.contextmanager
def use_context(ctx: Optional[PlanContext]):
    """Make ``ctx`` the active plan context for the dynamic extent (used
    at trace time; a None ctx is a no-op so wrappers stay unconditional)."""
    if ctx is None:
        yield
        return
    st = _stack()
    st.append(ctx)
    try:
        yield
    finally:
        st.pop()


def active_context() -> Optional[PlanContext]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def resolve_algo(ctx: PlanContext, site: str, axis: str, nbytes: int,
                 axis_size: int) -> str:
    """The ladder; returns an algo the SITE can execute."""
    if site not in SITE_ALGOS:
        raise ValueError(f"unknown comm-plan site {site!r} "
                         f"(known: {sorted(SITE_ALGOS)})")
    kind = SITE_KIND[site]
    supported = SITE_ALGOS[site]
    for key in (site, kind):
        forced = (ctx.overrides or {}).get(key)
        if forced is not None:
            if forced not in supported:
                raise ValueError(
                    f"comm_plan.overrides[{key!r}] = {forced!r} is not "
                    f"executable at site {site!r} (supported: "
                    f"{supported})")
            ctx.resolved[site] = forced
            return forced
    if ctx.plan is not None:
        chosen = ctx.plan.choose(kind, axis, nbytes)
        if chosen is not None and chosen in supported:
            ctx.resolved[site] = chosen
            return chosen
    algo = heuristic_algo(kind, nbytes, axis_size,
                          size_threshold=ctx.size_threshold)
    if algo not in supported:
        algo = "exact"
    ctx.resolved[site] = algo
    return algo


class AccuracyGuard:
    """Host-side exact-mode latch on small grad norms (see module doc)."""

    def __init__(self, min_grad_norm: float):
        self.min_grad_norm = float(min_grad_norm)
        self._last: Optional[float] = None

    def observe(self, grad_norm: float) -> None:
        if grad_norm == grad_norm:      # ignore NaN (overflow steps)
            self._last = float(grad_norm)

    @property
    def use_exact(self) -> bool:
        return self._last is not None and self._last < self.min_grad_norm


# ---------------------------------------------------------------------------
# local-region flag: inside the engine's stacked-grads shard_map the model
# runs SHARD-LOCALLY — mesh sharding constraints don't apply there (and
# naming a manual axis in one is an error on some jax versions)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def local_region(manual_axes=None):
    """Mark the dynamic extent as a shard-local model trace.

    With ``manual_axes=None`` (the legacy mode — pure-DP stacked step,
    MPMD stage programs) ``models.transformer._spec_constraint`` (and
    everything routed through it) becomes a no-op inside: every mesh
    constraint is meaningless in a fully shard-local trace.

    With ``manual_axes`` a set of axis names (the TP-composed stacked
    step, round 14), constraints are FILTERED instead: entries naming a
    manual axis are stripped (naming one inside the region is an error),
    entries naming auto axes — the model/TP layouts the partial-auto
    region still honors — survive and apply against the context mesh."""
    prev = getattr(_tls, "local_region", 0)
    prev_axes = getattr(_tls, "local_region_axes", None)
    _tls.local_region = prev + 1
    _tls.local_region_axes = (None if manual_axes is None
                              else frozenset(manual_axes))
    try:
        yield
    finally:
        _tls.local_region = prev
        _tls.local_region_axes = prev_axes


def in_local_region() -> bool:
    return bool(getattr(_tls, "local_region", 0))


def local_region_manual_axes():
    """The active region's manual-axes set, or None for the legacy
    suppress-everything mode (only meaningful under
    :func:`in_local_region`)."""
    return getattr(_tls, "local_region_axes", None)
