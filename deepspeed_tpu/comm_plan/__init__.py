"""Communication planning — per-collective algorithm selection.

The subsystem that decides, per {collective kind, mesh axis, message-size
bucket}, which wire format a collective runs with:

* :mod:`plan` — :class:`CommPlan`, the JSON-serializable decision table
  (round 14: the hand-overlapped schedules landed as the
  ``overlap``/``overlap_int8`` algorithm family — chunked
  allgather->matmul for the ZeRO-3 param fetch, chunked grad
  reduce-scatter for the ZeRO-2 sync, executors in
  ``runtime/comm/overlap.py``);
* :mod:`selector` — builds a plan from ``benchmarks/communication.py``
  sweep records (argmin latency per cell, deterministic tie-break) with
  safe size-threshold heuristics where no sweep exists;
* :mod:`runtime` — the active-plan context the engine installs around
  its traced programs plus the resolution ladder
  (override > plan entry > heuristic) and the accuracy guard;
* :mod:`cli` — ``dstpu comm-plan sweep|show``, recording sweeps through
  the ``autotuning/`` experiment machinery.

Execution lives next to the collectives it routes:
``runtime/comm/quantized.py`` (the int8 reduce-scatter / all-to-all) and
the ``comm.planned`` facade the engine and ``moe/`` dispatch call.
See docs/COMM.md.
"""

from .plan import ALGOS, CommPlan, PlanEntry, SITE_ALGOS, bucket_of
from .selector import heuristic_algo, parse_bench_lines, select_plan
from .runtime import (
    PlanContext,
    active_context,
    resolve_algo,
    use_context,
)

__all__ = ["ALGOS", "CommPlan", "PlanEntry", "SITE_ALGOS", "bucket_of",
           "heuristic_algo", "parse_bench_lines", "select_plan",
           "PlanContext", "active_context", "resolve_algo", "use_context"]
