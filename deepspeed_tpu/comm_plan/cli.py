"""``dstpu comm-plan`` — record collective sweeps and select a plan.

``sweep`` runs the (op x algo x size) grid through the ``autotuning/``
experiment machinery — every cell is an :class:`autotuning.Experiment`
whose runner times one collective via ``benchmarks/communication.py``,
scored by throughput exactly like a batch-geometry trial — then feeds
the measured rows to ``comm_plan.selector.select_plan`` and writes the
plan JSON the engine's ``comm_plan.plan_path`` consumes. ``show``
renders a recorded plan (and what the heuristic would do for a given
query) without touching devices.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def _sweep_records(ops: List[str], algos: List[str], sizes_mb: List[float],
                   dtype_name: str, iters: int,
                   mesh_spec: str = "") -> List[Dict]:
    """The grid, executed as autotuning experiments (GridSearchTuner over
    the op/algo/axis/size space; failed cells are recorded with their
    error and skipped by the selector, the autotuner's error-result
    convention). With ``mesh_spec`` ('data=2,model=4') the grid gains an
    AXIS dimension — one row per >1-member mesh axis per cell, so
    hierarchical ICI/DCN selection (e.g. exact on the fast axis, int8 on
    the slow one) has per-axis measurements to choose from; the plan's
    wildcard resolution already preferred exact-axis rows, the sweep
    just never fed it."""
    import jax
    import jax.numpy as jnp

    from ..autotuning.autotuner import Autotuner
    from ..benchmarks.communication import (OP_ALGOS, build_mesh,
                                            run_op_sweep, sweep_axes)

    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[dtype_name]
    mesh = build_mesh(mesh_spec)
    rows: List[Dict] = []

    def runner(cfg: Dict) -> Dict[str, float]:
        op, algo, mb = cfg["op"], cfg["algo"], float(cfg["size_mb"])
        if algo not in OP_ALGOS.get(op, ()):
            raise ValueError(f"no {algo} implementation for {op}")
        row = run_op_sweep(op, [mb], dtype, iters, algo=algo,
                           emit=True, mesh=mesh, axis=cfg["axis"])[0]
        rows.append(row)
        return {"throughput": row["busbw_gbps"],
                "latency_us": row["latency_us"]}

    tuner = Autotuner(
        base_config={},
        runner=runner,
        tuning_space={"op": ops, "algo": algos, "size_mb": sizes_mb,
                      "axis": sweep_axes(mesh)},
        tuner_type="gridsearch")
    tuner.tune()
    n_fail = sum(1 for e in tuner.experiments if e.error)
    if n_fail:
        print(f"comm-plan sweep: {n_fail} cells failed (recorded with "
              "errors, excluded from selection)")
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu comm-plan",
        description="record collective sweeps / select + inspect comm "
                    "plans (docs/COMM.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sw = sub.add_parser("sweep", help="run the op x algo x size grid on "
                                      "this host's devices and write the "
                                      "selected plan")
    sw.add_argument("--ops", default="all_reduce,all_gather,"
                                     "reduce_scatter,all_to_all")
    sw.add_argument("--algos", default="exact,int8,overlap,overlap_int8",
                    help="wire formats/schedules per op; unsupported "
                         "(op, algo) pairs are recorded as failed cells "
                         "and skipped by the selector")
    sw.add_argument("--sizes-mb", default="1,4,16,64")
    sw.add_argument("--dtype", default="float32")
    sw.add_argument("--iters", type=int, default=10)
    sw.add_argument("--mesh", default="",
                    help="named mesh spec 'data=2,model=4': sweep each "
                         ">1-member axis separately (per-axis plan rows "
                         "for hierarchical meshes); empty = flat 'all'")
    sw.add_argument("--out", default="comm_plan.json",
                    help="plan JSON path (engine: comm_plan.plan_path)")
    sw.add_argument("--record", default="",
                    help="also save the raw sweep rows (the regression "
                         "baseline benchmarks/communication.py compares "
                         "against)")

    sh = sub.add_parser("show", help="render a recorded plan")
    sh.add_argument("plan", help="plan JSON path")
    sh.add_argument("--query", default="",
                    help="kind:axis:bytes — print the algorithm this "
                         "plan (entry or heuristic) resolves for one "
                         "message, e.g. reduce_scatter:data:8388608")

    args = p.parse_args(argv)
    from .plan import CommPlan
    if args.cmd == "show":
        plan = CommPlan.load(args.plan)
        print(plan.describe())
        if args.query:
            from .selector import heuristic_algo
            kind, axis, nbytes = args.query.split(":")
            chosen = plan.choose(kind, axis, int(nbytes))
            if chosen is None:
                chosen = heuristic_algo(kind, int(nbytes), axis_size=2)
                print(f"{args.query} -> {chosen} (heuristic: no plan "
                      "entry covers this bucket)")
            else:
                print(f"{args.query} -> {chosen} (plan entry)")
        return 0

    import jax
    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    algos = [a.strip() for a in args.algos.split(",") if a.strip()]
    sizes = [float(s) for s in args.sizes_mb.split(",")]
    rows = _sweep_records(ops, algos, sizes, args.dtype, args.iters,
                          mesh_spec=args.mesh)
    if args.record:
        from ..benchmarks.communication import record_sweep
        print(f"comm-plan sweep recorded: "
              f"{record_sweep(rows, args.record)}")
    from .selector import select_plan
    plan = select_plan(rows, meta={"n_devices": len(jax.devices()),
                                   "dtype": args.dtype,
                                   "source": "dstpu comm-plan sweep"})
    path = plan.save(args.out)
    print(plan.describe())
    print(f"comm-plan written: {path} ({len(plan.entries)} entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
