"""Long-context + MoE training throughput legs (single chip).

Fills the two perf-evidence gaps left after the pipeline/serving tables:
  - long-context training: the Pallas flash-attention path at seq 4k/8k,
    where the reference's answer was block-sparse attention (its dense
    kernels stop at ~1-2k; docs/_pages/training.md:108 claims 10x longer
    sequences via sparsity). Flash attention holds dense-exact math at
    those lengths; the reference-impl comparison leg quantifies what the
    kernel buys.
  - MoE training: GShard top-1 dispatch at 350m scale, TFLOPs accounted
    on ACTIVE params (6N with N = params a token actually touches), so
    the number is comparable to the dense 350m leg.

Usage: python scripts/longctx_moe_bench.py [--steps N]
Prints one JSON line per leg (same schema as bench.py) and a markdown
table for docs/BENCHMARKS.md.
"""

import argparse
import gc
import json
import sys

sys.path.insert(0, ".")  # run from the repo root (PYTHONPATH breaks axon)


def run(legs=None, steps=6):
    import jax
    from deepspeed_tpu.benchmarks.training_bench import run_training_bench

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        print("WARNING: not on TPU; numbers are smoke only", file=sys.stderr)

    all_legs = {
        # seq, micro, gas, extra model kwargs
        "350m-seq4k-flash": dict(preset="gpt2-350m", seq=4096, micro=2,
                                 gas=8, attention_impl="flash"),
        "350m-seq4k-reference": dict(preset="gpt2-350m", seq=4096, micro=2,
                                     gas=8, attention_impl="reference"),
        "350m-seq8k-flash": dict(preset="gpt2-350m", seq=8192, micro=1,
                                 gas=8, attention_impl="flash"),
        # 4 experts turn the 350m trunk into ~0.96B total params: pure-bf16
        # state (6 bytes/param) is what fits them on one 16 GB chip. 8
        # experts (~1.8B) reproducibly kill this environment's remote AOT
        # compile helper (HTTP 500, subprocess exit 1) — the same-size dense
        # 1.3B program compiles, so the limit is the helper's memory on the
        # grouped-dispatch MoE graph, not the model code.
        "350m-moe4": dict(preset="gpt2-350m", seq=1024, micro=8, gas=4,
                          moe_experts=4, moe_capacity_factor=1.25,
                          pure_bf16=True, grad_accum_dtype="bf16"),
    }
    rows = []
    for name, kw in all_legs.items():
        if legs and name not in legs:
            continue
        kw = dict(kw)
        preset = kw.pop("preset")
        try:
            r = run_training_bench(
                preset, seq=kw.pop("seq"), micro=kw.pop("micro"),
                gas=kw.pop("gas"), steps=steps, zero_stage=1, remat=True,
                remat_policy="dots", fused_loss=True, verbose=False,
                pure_bf16=kw.pop("pure_bf16", False),
                grad_accum_dtype=kw.pop("grad_accum_dtype", None), **kw)
        except Exception as e:  # OOM legs are data, not failures
            print(json.dumps({"leg": name, "error": repr(e)[:300]}),
                  flush=True)
            continue
        r["leg"] = name
        print(json.dumps(r), flush=True)
        d = r["detail"]
        rows.append((name, d["seq"], d["micro"] * d["gas"], r["value"],
                     d["tflops_incl_attention"], d.get("mfu_incl_attention"),
                     d["step_time_s"], d["samples_per_s"]))
        gc.collect()
        jax.clear_caches()

    print("\n| leg | seq | batch | TF/chip (6N) | TF incl attn | MFU | "
          "step s | samples/s |")
    print("|---|---|---|---|---|---|---|---|")
    for name, seq, batch, tf, tfa, mfu, dt, sps in rows:
        mfu_s = f"{mfu:.0%}" if mfu else "—"
        print(f"| {name} | {seq} | {batch} | {tf:.1f} | {tfa:.1f} | "
              f"{mfu_s} | {dt:.2f} | {sps:.2f} |")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("legs", nargs="*", help="subset of leg names")
    a = p.parse_args()
    run(legs=a.legs or None, steps=a.steps)
