#!/usr/bin/env bash
# Tier-2 test pass: everything tier-1 skips via -m 'not slow'.
#
# Two populations live behind the `slow` marker:
#   - multi-second subprocess matrices (engine-in-child chaos/supervision
#     tests) — also run by scripts/chaos.sh;
#   - heavy model-integration legs (multi-step training parity, 2-proc
#     gloo TP+PP, HF parity, remat/fused-loss agreement, the round-8
#     serving architecture matrix) that were moved out of tier-1 to keep
#     its wall clock inside the 870s budget on 2-core CI hosts. Each has
#     a cheaper cousin still gating tier-1.
#
# Run this after any change to runtime/, models/, inference/, or
# serving/ that tier-1 alone can't be trusted to cover.
set -euo pipefail
cd "$(dirname "$0")/.."

exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/ -q -m slow \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@"
