"""Perf sweep: gpt2-350m train-step throughput across config points.

Run on the real chip. Each point prints one JSON line; the last line is the
ranked summary. Thin wrapper over benchmarks.training_bench (the autotuner's
grid search is the production version of this loop).

Round-2 findings (v5e, 15.75GB HBM): micro>=32 or remat=False OOM at compile
for gpt2-350m/seq1024; micro16 x gas16 with "dots" remat is the feasible
optimum (~70 TFLOPs/chip) and is what bench.py ships.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from deepspeed_tpu.benchmarks.training_bench import run_training_bench

    points = [
        # (micro, gas, remat, policy)    batch fixed at 256
        (16, 16, True, "dots"),     # current bench config
        (32, 8, True, "dots"),
        (32, 8, False, "dots"),
        (16, 16, False, "dots"),
        (64, 4, True, "dots"),
        (32, 8, True, "full"),
    ]
    if len(sys.argv) > 1:      # run a single point by index
        points = [points[int(sys.argv[1])]]
    results = []
    for (micro, gas, remat, pol) in points:
        try:
            r = run_training_bench("gpt2-350m", seq=1024, micro=micro,
                                   gas=gas, steps=3, remat=remat,
                                   remat_policy=pol, verbose=False)
            rec = {"micro": micro, "gas": gas, "remat": remat, "policy": pol,
                   "tflops": r["value"], "step_s": r["detail"]["step_time_s"]}
        except Exception as e:  # OOM etc. — record and continue
            rec = {"micro": micro, "gas": gas, "remat": remat, "policy": pol,
                   "error": str(e)[:200]}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    ranked = sorted([r for r in results if "tflops" in r],
                    key=lambda r: -r["tflops"])
    print(json.dumps({"ranked": ranked[:3]}))


if __name__ == "__main__":
    main()
