"""Perf sweep: gpt2-350m train-step throughput across config points.

Run on the real chip. Each point prints one JSON line; the last line is the
ranked summary. Used to pick bench.py's tuned config (the autotuner's
grid search is the production version of this loop).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def measure(preset, micro, gas, seq, remat, remat_policy, block_q, block_k,
            steps=3):
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, fused_loss_passthrough

    model, cfg = build_model(preset, max_seq_len=seq, remat=remat,
                             remat_policy=remat_policy, fused_loss=True,
                             loss_chunk=256)
    batch_size = micro * gas
    config = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 1000,
    }
    rng = np.random.default_rng(0)

    def make_batch():
        return {"input_ids": rng.integers(0, cfg.vocab_size,
                                          size=(batch_size, seq))}

    engine, *_ = ds.initialize(model=model, config=config,
                               loss_fn=fused_loss_passthrough,
                               example_batch=make_batch())
    float(engine.train_batch(make_batch())["loss"])
    float(engine.train_batch(make_batch())["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(make_batch())
    float(m["loss"])
    float(jax.tree.leaves(engine.state.params)[0].ravel()[0])
    dt = (time.perf_counter() - t0) / steps
    tflops = 6.0 * cfg.num_params() * batch_size * seq / dt / 1e12
    return tflops, dt


def main():
    points = [
        # (micro, gas, remat, policy, bq, bk)   batch fixed at 256
        (16, 16, True, "dots", None, None),     # current bench config
        (32, 8, True, "dots", None, None),
        (32, 8, False, "dots", None, None),
        (16, 16, False, "dots", None, None),
        (64, 4, True, "dots", None, None),
        (32, 8, True, "full", None, None),
    ]
    if len(sys.argv) > 1:      # run a single point by index
        points = [points[int(sys.argv[1])]]
    results = []
    for (micro, gas, remat, pol, bq, bk) in points:
        try:
            tf, dt = measure("gpt2-350m", micro, gas, 1024, remat, pol, bq, bk)
            rec = {"micro": micro, "gas": gas, "remat": remat, "policy": pol,
                   "bq": bq, "bk": bk, "tflops": round(tf, 2),
                   "step_s": round(dt, 4)}
        except Exception as e:  # OOM etc. — record and continue
            rec = {"micro": micro, "gas": gas, "remat": remat, "policy": pol,
                   "bq": bq, "bk": bk, "error": str(e)[:200]}
        print(json.dumps(rec), flush=True)
        results.append(rec)
    ranked = sorted([r for r in results if "tflops" in r],
                    key=lambda r: -r["tflops"])
    print(json.dumps({"ranked": ranked[:3]}))


if __name__ == "__main__":
    main()
