#!/usr/bin/env bash
# graftlint CI entrypoint: machine-readable lint over the package.
#
#   scripts/lint.sh                   # JSON report on stdout, exit 1 on gating findings
#   scripts/lint.sh --format text     # human-readable
#   scripts/lint.sh path/to/file.py   # lint a subset
#   scripts/lint.sh --changed         # fast mode: only .py files changed vs main
#   scripts/lint.sh --sarif out.sarif # additionally write SARIF 2.1.0 (CI PR annotation)
#   scripts/lint.sh --fix             # apply autofixes, then lint
#   scripts/lint.sh --timing          # per-rule wall time on stderr
#   scripts/lint.sh --rules TPU022,TPU023        # only these rules
#   scripts/lint.sh --exclude-rules TPU016       # all but these
#
# The checked-in baseline (.graftlint.json) is applied automatically; a
# finding not in the baseline and not suppressed inline fails the run.
# See docs/LINT.md for the rule catalog and workflows.
set -euo pipefail
cd "$(dirname "$0")/.."

FORMAT="json"
CHANGED=0
EXTRA=()
ARGS=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --format) FORMAT="$2"; shift 2 ;;
    --changed) CHANGED=1; shift ;;
    --sarif) EXTRA+=("--sarif" "$2"); shift 2 ;;
    --fix) EXTRA+=("--fix"); shift ;;
    --timing) EXTRA+=("--timing"); shift ;;
    --rules|--select) EXTRA+=("--select" "$2"); shift 2 ;;
    --exclude-rules|--ignore) EXTRA+=("--ignore" "$2"); shift 2 ;;
    *) ARGS+=("$1"); shift ;;
  esac
done

if [[ "$CHANGED" == "1" ]]; then
  # fast mode: lint only package .py files that differ from main (committed
  # or working-tree). Falls back to the full package when main is unknown.
  BASE="$(git merge-base HEAD main 2>/dev/null || echo "")"
  if [[ -n "$BASE" ]]; then
    mapfile -t FILES < <( { git diff --name-only --diff-filter=d "$BASE" -- 'deepspeed_tpu/*.py' 'deepspeed_tpu/**/*.py'; \
                            git diff --name-only --diff-filter=d -- 'deepspeed_tpu/*.py' 'deepspeed_tpu/**/*.py'; } | sort -u )
    if [[ ${#FILES[@]} -eq 0 ]]; then
      echo "graftlint: no package files changed vs main" >&2
      exit 0
    fi
    ARGS+=("${FILES[@]}")
  fi
fi

exec python -m deepspeed_tpu.analysis "${ARGS[@]:-deepspeed_tpu}" --format "$FORMAT" ${EXTRA[@]+"${EXTRA[@]}"}
