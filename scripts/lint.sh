#!/usr/bin/env bash
# graftlint CI entrypoint: machine-readable lint over the package.
#
#   scripts/lint.sh                 # JSON report on stdout, exit 1 on gating findings
#   scripts/lint.sh --format text   # human-readable
#   scripts/lint.sh path/to/file.py # lint a subset
#
# The checked-in baseline (.graftlint.json) is applied automatically; a
# finding not in the baseline and not suppressed inline fails the run.
# See docs/LINT.md for the rule catalog and workflows.
set -euo pipefail
cd "$(dirname "$0")/.."

FORMAT="json"
ARGS=()
while [[ $# -gt 0 ]]; do
  case "$1" in
    --format) FORMAT="$2"; shift 2 ;;
    *) ARGS+=("$1"); shift ;;
  esac
done

exec python -m deepspeed_tpu.analysis "${ARGS[@]:-deepspeed_tpu}" --format "$FORMAT"
