"""Convergence sanity harness (reference analogue:
tests/model/Megatron_GPT2/run_sanity_check.py — loss-curve agreement
across configs, not unit-step equality).

Two legs:
  1. CHIP: GPT-2-125M, a few hundred REAL optimizer steps under ZeRO
     stages 0/1/2/3 with identical seed + data order; the four loss
     curves must overlap within tolerance (the stages are layout
     transforms of the same math, so curve divergence = sharding bug).
  2. CPU MESH (8 virtual devices, re-exec'd subprocess like the dryrun):
     a small model trained to convergence under dense DP vs GPipe(pp=2)
     vs 1F1B(pp=2) — the pipeline schedules must track the dense curve.

Data is synthetic but LEARNABLE: per-sample arithmetic token sequences
(next = prev + delta mod V, delta inferable in-context) with 5% noise, so
the loss falls far below the uniform floor and a broken optimizer or
schedule shows up as a flat/diverging curve, which pure-random tokens
would mask.

Usage:  python scripts/convergence.py [--steps 250]
        (run from the repo root; needs the TPU chip for leg 1)
"""
import argparse
import json
import os
import subprocess
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_batches(vocab, steps, batch, seq, seed=0):
    """[steps, batch, seq] int32: arithmetic sequences mod vocab + 5% noise."""
    rng = np.random.default_rng(seed)
    deltas = rng.integers(1, 17, size=(steps, batch, 1))
    start = rng.integers(0, vocab, size=(steps, batch, 1))
    pos = np.arange(seq)[None, None, :]
    ids = (start + deltas * pos) % vocab
    noise = rng.random((steps, batch, seq)) < 0.05
    ids = np.where(noise, rng.integers(0, vocab, size=ids.shape), ids)
    return ids.astype(np.int32)


def run_stage(stage, ids, preset="gpt2-125m", seq=512, micro=8,
              pure_bf16=False, log_every=50):
    import gc

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_model, fused_loss_passthrough

    steps = ids.shape[0]
    model, cfg = build_model(preset, max_seq_len=seq, remat=True,
                             remat_policy="dots", fused_loss=True,
                             loss_chunk=256)
    config = {
        "train_batch_size": micro,
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4,
                                                  "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 20}},
        "bf16": {"enabled": True, "master_weights": not pure_bf16},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "seed": 1234,
    }
    engine, *_ = ds.initialize(
        model=model, config=config, loss_fn=fused_loss_passthrough,
        example_batch={"input_ids": ids[0]})
    losses = []
    for i in range(steps):
        m = engine.train_batch({"input_ids": ids[i]})
        losses.append(float(m["loss"]))
        if log_every and (i + 1) % log_every == 0:
            print(f"    stage {stage} step {i+1}: {losses[-1]:.4f}",
                  flush=True)
    del engine, model
    gc.collect()
    jax.clear_caches()
    return losses


def chip_leg(steps):
    import jax
    assert jax.default_backend() == "tpu", (
        "leg 1 needs the chip; found " + jax.default_backend())
    from deepspeed_tpu.models import build_model
    _, cfg = build_model("gpt2-125m")
    ids = make_batches(cfg.vocab_size, steps, batch=8, seq=512, seed=0)
    curves = {}
    for stage in (0, 1, 2, 3):
        print(f"  ZeRO-{stage} x {steps} steps on the chip", flush=True)
        curves[f"zero{stage}"] = run_stage(stage, ids)
    return curves


CPU_LEG = r"""
import os, sys, json
sys.path.insert(0, os.environ["DSTPU_CONV_REPO"])
import numpy as np
import jax
import deepspeed_tpu as ds
from deepspeed_tpu.models import build_model, causal_lm_loss
from deepspeed_tpu.models.pipeline import build_pipelined_model
sys.path.insert(0, os.path.join(os.environ["DSTPU_CONV_REPO"], "scripts"))
from convergence import make_batches

steps = int(os.environ["DSTPU_CONV_STEPS"])
V, SEQ, B = 256, 64, 16
ids = make_batches(V, steps, batch=B, seq=SEQ, seed=1)
kw = dict(hidden_size=128, num_layers=4, num_heads=4, vocab_size=V,
          max_seq_len=SEQ, attention_impl="reference")
base_cfg = {
    # same GLOBAL batch (16) in every config so the curves are comparable;
    # micro/gas/dp split differs by topology: dense dp=8 -> 2x1x8,
    # pipelined pp=2 => dp=4 -> 2x2x4
    "train_batch_size": B,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 1},
    "gradient_clipping": 1.0,
    "seed": 99,
}
curves = {}
for label in ("dense", "gpipe", "1f1b"):
    config = dict(base_cfg,
                  train_micro_batch_size_per_gpu=2,
                  gradient_accumulation_steps=1 if label == "dense" else 2)
    if label == "dense":
        model, cfg = build_model("gpt2-tiny", **kw)
    else:
        model, cfg = build_pipelined_model("gpt2-tiny", pp=2, n_micro=2,
                                           **kw)
        config["pipeline"] = ({"stages": 2} if label == "gpipe"
                              else {"stages": 2, "schedule": "1f1b"})
    eng, *_ = ds.initialize(model=model, config=config,
                            loss_fn=causal_lm_loss,
                            example_batch={"input_ids": ids[0]})
    ls = [float(eng.train_batch({"input_ids": ids[i]})["loss"])
          for i in range(steps)]
    curves[label] = ls
    print(f"  {label}: start {ls[0]:.4f} final {ls[-1]:.4f}", flush=True)
with open(os.environ["DSTPU_CONV_OUT"], "w") as f:
    json.dump(curves, f)
"""


def cpu_leg(steps, out_path):
    from deepspeed_tpu.utils.respawn import clean_cpu_env
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_cpu_env(8)
    # no PYTHONPATH: CPU_LEG sys.path.inserts the repo itself, and
    # PYTHONPATH=/root/repo breaks axon backend registration if this env
    # ever reaches a chip-side process
    env.update(DSTPU_CONV_REPO=repo, DSTPU_CONV_STEPS=str(steps),
               DSTPU_CONV_OUT=out_path)
    proc = subprocess.run([sys.executable, "-u", "-c", CPU_LEG], env=env,
                          cwd=repo, timeout=3600)
    assert proc.returncode == 0, f"cpu leg rc={proc.returncode}"
    with open(out_path) as f:
        return json.load(f)


def summarize(curves, ref_key, tol_final, tol_max, skip=20):
    """Max pointwise gap vs the reference curve after warmup + final gap."""
    ref = np.asarray(curves[ref_key])
    skip = min(skip, max(len(ref) - 1, 0))   # short runs: compare the tail
    rows = []
    ok = True
    for k, v in curves.items():
        v = np.asarray(v)
        gap = np.abs(v[skip:] - ref[skip:])
        row = {"config": k, "start": round(float(v[0]), 4),
               "final": round(float(v[-1]), 4),
               "max_gap": round(float(gap.max()), 4),
               "final_gap": round(float(abs(v[-1] - ref[-1])), 4)}
        row["pass"] = bool(row["max_gap"] <= tol_max
                           and row["final_gap"] <= tol_final)
        ok &= row["pass"]
        rows.append(row)
    return rows, ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--cpu-steps", type=int, default=200)
    ap.add_argument("--out", default="docs/convergence_r05.json")
    ap.add_argument("--skip-chip", action="store_true")
    ap.add_argument("--skip-cpu", action="store_true")
    args = ap.parse_args()

    result = {"steps_chip": args.steps, "steps_cpu": args.cpu_steps}
    if not args.skip_chip:
        print("leg 1: ZeRO-0/1/2/3 @ gpt2-125m on the chip", flush=True)
        chip = chip_leg(args.steps)
        rows, ok = summarize(chip, "zero0", tol_final=0.05, tol_max=0.25)
        result["chip"] = {"curves": chip, "summary": rows, "ok": ok}
        for r in rows:
            print("  ", r, flush=True)
    if not args.skip_cpu:
        print("leg 2: dense vs gpipe vs 1f1b @ tiny on the 8-dev CPU mesh",
              flush=True)
        cpu = cpu_leg(args.cpu_steps, "/tmp/conv_cpu.json")
        rows, ok = summarize(cpu, "dense", tol_final=0.05, tol_max=0.25)
        result["cpu"] = {"curves": cpu, "summary": rows, "ok": ok}
        for r in rows:
            print("  ", r, flush=True)

    with open(args.out, "w") as f:
        json.dump(result, f)
    legs = [k for k in ("chip", "cpu") if k in result]
    all_ok = bool(legs) and all(result[k]["ok"] for k in legs)
    print(f"convergence: {'OK' if all_ok else 'DIVERGED'} -> {args.out}",
          flush=True)
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
