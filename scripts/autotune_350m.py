"""Cold-start autotune of the gpt2-350m training config on the real chip.

The round-3 hand-tuned bench config (micro 16 x gas 16, selective "dots"
remat) took manual sweeps to find; this script hands the same search to the
autotuner — space: micro-batch ladder x remat policy, model-based tuner,
stale-trial early stop — and records whether it rediscovers (>=95% of) the
hand-tuned throughput unattended. Results land in docs/BENCHMARKS.md.

    python scripts/autotune_350m.py [--trials 8]
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--trials", type=int, default=8)
    p.add_argument("--steps", type=int, default=3)
    args = p.parse_args()

    import gc

    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.autotuning import Autotuner
    from deepspeed_tpu.models import build_model, fused_loss_passthrough

    SEQ = 1024
    GLOBAL_BATCH = 256

    def runner(config, slot=None, deadline=None):
        config = dict(config)           # the experiment record keeps the
        remat_policy = config.pop("_remat_policy")   # full config incl. knob
        model, cfg = build_model("gpt2-350m", max_seq_len=SEQ,
                                 remat=remat_policy is not None,
                                 remat_policy=remat_policy or "dots",
                                 fused_loss=True, loss_chunk=256)
        rng = np.random.default_rng(0)

        def batch(_i):
            return {"input_ids": rng.integers(
                0, cfg.vocab_size, size=(GLOBAL_BATCH, SEQ))}

        engine, *_ = ds.initialize(model=model, config=config,
                                   loss_fn=fused_loss_passthrough,
                                   example_batch=batch(0))
        try:
            float(engine.train_batch(batch(0))["loss"])   # compile
            times = []
            for i in range(args.steps):
                t0 = time.perf_counter()
                float(engine.train_batch(batch(i))["loss"])
                times.append(time.perf_counter() - t0)
                if deadline is not None:
                    rem = deadline()
                    if rem is not None and rem <= 0:
                        raise RuntimeError("killed: losing config")
            dt = float(np.median(times))
            return {"throughput": GLOBAL_BATCH / dt, "step_time": dt}
        finally:
            del engine
            gc.collect()
            jax.clear_caches()

    base = {
        "train_batch_size": GLOBAL_BATCH,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
    }
    space = {
        "train_micro_batch_size_per_gpu": [4, 8, 16, 32],
        "_remat_policy": [None, "dots"],
    }
    at = Autotuner(base, runner, tuning_space=space, tuner_type="model",
                   num_trials=args.trials, early_stopping=4,
                   results_dir="/tmp/autotune_350m")
    t0 = time.perf_counter()
    at.tune()
    wall = time.perf_counter() - t0
    best = at.best()
    print(json.dumps({
        "best_overrides": best.overrides,
        "best_throughput_samples_s": round(best.score, 2),
        "n_experiments": len(at.experiments),
        "wall_s": round(wall, 1),
        "ranking": [{"name": e.name,
                     "tput": (round(e.score, 1)
                              if e.metrics else e.error and e.error[:60])}
                    for e in at.experiments],
    }, indent=2))


if __name__ == "__main__":
    main()
