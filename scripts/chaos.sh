#!/usr/bin/env bash
# Fault-injection suite, standalone: crash a real checkpoint save at every
# named failpoint (plus kill-mid-write and SIGTERM subprocess tests) and
# prove resume. See docs/RESILIENCE.md for the failpoint catalog.
#
#   scripts/chaos.sh              # full crash-safety suite
#   scripts/chaos.sh -k sigterm   # subset (pytest -k forwarded)
set -euo pipefail
cd "$(dirname "$0")/.."

# determinism: the suite arms its own failpoints; a stray env spec would
# fire inside arbitrary tests (tests/conftest.py also scrubs this)
unset DSTPU_CHAOS

exec env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
    -p no:cacheprovider "$@"
