#!/usr/bin/env bash
# Fault-injection suite, standalone: crash a real checkpoint save at every
# named failpoint (plus kill-mid-write and SIGTERM subprocess tests), prove
# resume, and drive the run-supervision matrices — fail-fast teardown,
# phase-aware watchdog (compile-hang stack-dump/rc-117), heartbeat-loss and
# heartbeat-silence detection (RunSupervisor + BackendSupervisor incl. the
# backend kill path), blackholed-host blacklisting with degraded-world
# elastic resume, connect retries, rc-114 end-to-end through dstpu
# --elastic, and the per-rank failpoint in the REAL 2-process sharded save.
# Round 7 adds the training-integrity matrices: chaos grad spike -> in-jit
# skip with loss parity, spike storm -> verified rollback + data
# fast-forward, post-rollback reproduction -> rc-118 abort, and the
# cross-replica SDC bit-flip -> detection + host attribution (single-proc
# 8-device vote and the REAL 2-process world).
# Round 11 adds the serving-fleet matrices (tests/test_fleet.py): replica
# kill mid-decode -> exactly-once requeue with token-exact outputs,
# replica hang -> heartbeat-silence detection + blacklist/parole,
# retry-budget exhaustion -> FAILED, requeue-crash -> orphan retry, and
# serve.oom under the fleet.
# Round 15 adds the straggler-defense matrices (tests/test_straggler.py +
# the test_fleet straggler legs): a run.slow-degraded rank self-flags over
# the shared heartbeat channel, aborts rc 117, is struck and blacklisted
# by DSElasticAgent with the degraded world resuming training; a
# serve.replica_slow-degraded replica is drained exactly-once token-exact
# and blacklisted on repeat, with the poisson_fleet_slow bench row.
# Round 17 adds the low-precision training leg (tests/test_low_precision.py):
# chaos grad spike on a sentinel-gated int8 fake-quant engine -> in-jit
# skip + loss parity with the uninjected low-precision twin — the
# guardrail the activation_quant experiment is gated on, fired under it.
# Round 12 adds the disaggregated-serving matrices (tests/test_disagg.py):
# replica kill at serve.chunk / serve.handoff / serve.handoff_drop ->
# every request completes token-exact or FAILED-within-retry-budget with
# the SHARED pool's refcount accounting balanced after recovery, plus
# handoff backpressure/deadline units and chunk-progress carry.
# Round 19 adds the traffic-shaping matrices (tests/test_autoscale.py):
# serve.scale_up crash -> slot rollback with the fleet unchanged,
# scale-down-during-kill -> death concludes `retired` with exactly-once
# token-exact requeue and no replacement, serve.preempt crash ->
# orphan-parked victim resumed token-exact even when its old replica
# dies in the same window, plus the overload-ladder shed/reject legs and
# the process-placement autoscale/preempt (slow) legs.
# Includes the `slow`-marked engine-in-child tests tier-1 skips.
# See docs/RESILIENCE.md for the failpoint catalog and exit-code contract.
#
#   scripts/chaos.sh              # full crash-safety + supervision suite
#   scripts/chaos.sh -k sigterm   # subset (pytest -k forwarded)
set -euo pipefail
cd "$(dirname "$0")/.."

# determinism: the suite arms its own failpoints; a stray env spec would
# fire inside arbitrary tests (tests/conftest.py also scrubs this)
unset DSTPU_CHAOS

exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_chaos.py \
    tests/test_sentinel.py \
    tests/test_supervisor.py \
    tests/test_heartbeat.py \
    tests/test_multinode_runner.py \
    tests/test_launcher_elastic.py \
    tests/test_fleet.py \
    tests/test_autoscale.py \
    tests/test_straggler.py \
    tests/test_disagg.py \
    tests/test_low_precision.py \
    tests/test_mpmd.py \
    "tests/test_multiprocess.py::test_two_process_sharded_save_with_per_rank_failpoint" \
    "tests/test_multiprocess.py::test_two_process_sdc_bitflip_detected_and_attributed" \
    -q -p no:cacheprovider "$@"
