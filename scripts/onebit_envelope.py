"""Quantify the 1-bit / 0/1-Adam state-memory envelope (VERDICT r4 item 7).

Measures REAL per-device optimizer-state bytes (from each leaf's actual
shards on an 8-virtual-device CPU mesh) for:

  - AdamW + ZeRO-1            (the baseline the 1-bit family gives up)
  - OneBitAdam  (zero_stage 1, past freeze_step — compression phase)
  - ZeroOneAdam (zero_stage 1, past var_freeze_step — local-step phase)

and extrapolates bytes/param/device to 1.3B scale. Run:
    python scripts/onebit_envelope.py
(re-execs itself onto the CPU mesh; prints a markdown table.)
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_13B = 1.314e9        # gpt2-1.3b param count the bench legs use


def per_device_bytes(tree):
    """Worst-device resident bytes of a pytree, from each leaf's REAL
    shards (also imported by test_onebit.py's memory-model regression)."""
    import jax
    dev = {}
    for leaf in jax.tree.leaves(tree):
        for sh in leaf.addressable_shards:
            dev[sh.device] = dev.get(sh.device, 0) + sh.data.nbytes
    return max(dev.values()) if dev else 0


def _measure():
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu as ds

    n_dev = len(jax.devices())

    def breakdown(state):
        return {k: per_device_bytes(v) for k, v in state.items()
                if k != "lrs"}

    # plain MLP regressor: the 1-bit runners are pure-DP and own the whole
    # step (the Transformer's internal sharding constraints are for the
    # SPMD engine path); the state layout only depends on the param TREE,
    # so any tree of realistic leaf shapes measures the envelope
    H = 512

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=False):
            x = batch["x"]
            for _ in range(4):
                x = nn.tanh(nn.Dense(H)(x))
            y = nn.Dense(1)(x)
            return jnp.mean((y[:, 0] - batch["y"]) ** 2)

    rng = np.random.default_rng(0)
    batch = {"x": rng.standard_normal((2 * n_dev, H)).astype(np.float32),
             "y": rng.standard_normal((2 * n_dev,)).astype(np.float32)}
    model = MLP()
    n_params = 4 * (H * H + H) + H + 1

    def run(opt_type, opt_params, steps):
        config = {
            "train_batch_size": 2 * n_dev,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": opt_type, "params": opt_params},
            "zero_optimization": {"stage": 1},
            "seed": 5,
        }
        eng, *_ = ds.initialize(model=model, config=config,
                                example_batch=batch)
        for _ in range(steps):
            eng.train_batch(batch)
        return eng

    rows = {}
    eng = run("AdamW", {"lr": 1e-3}, steps=2)
    rows["adamw_zero1"] = {"total": per_device_bytes(eng.state.opt_state)}
    del eng

    eng = run("OneBitAdam", {"lr": 1e-3, "freeze_step": 4}, steps=8)
    st = eng.state.opt_state["onebit"]
    rows["onebit_zero1_postfreeze"] = dict(breakdown(st),
                                           total=per_device_bytes(st))
    del eng

    eng = run("ZeroOneAdam", {"lr": 1e-3, "var_freeze_step": 4,
                              "var_update_scaler": 2,
                              "local_step_scaler": 4,
                              "local_step_clipper": 4}, steps=10)
    st = eng.state.opt_state["onebit"]
    rows["zeroone_zero1_localphase"] = dict(breakdown(st),
                                            total=per_device_bytes(st))

    print(json.dumps({"n_devices": n_dev, "n_params": n_params,
                      "rows": rows}))


def main():
    from deepspeed_tpu.utils.respawn import clean_cpu_env
    env = clean_cpu_env(8)
    proc = subprocess.run(
        [sys.executable, "-u", "-c",
         f"import sys; sys.path.insert(0, {REPO!r}); "
         "from scripts.onebit_envelope import _measure; _measure()"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-4000:])
        sys.exit(1)
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    n, N = data["n_devices"], data["n_params"]
    print(f"measured on {n} devices, model N = {N/1e6:.2f}M params\n")
    print("| optimizer (ZeRO-1) | state bytes/param/device | at 1.3B "
          "(GB/device, fp32) | breakdown (bytes/param) |")
    print("|---|---|---|---|")
    for name, row in data["rows"].items():
        bpp = row["total"] / N
        gb = bpp * N_13B / 2**30
        det = ", ".join(f"{k} {v / N:.2f}" for k, v in sorted(row.items())
                        if k != "total")
        print(f"| {name} | {bpp:.2f} | {gb:.1f} | {det} |")


if __name__ == "__main__":
    main()
